"""Checkpoint/resume ≡ tests/L0/run_amp/test_checkpointing.py: scaler
state round-trips, optimizer/model state round-trips, auto-resume —
plus the ISSUE 9 preemption-proof stack: sharded-manifest commit
atomicity, chaos fail points/corruption, elastic dp=N→M re-layout,
CheckpointManager async saves + MetricsLogger ckpt_* stamps, the
flight-recorder resume guard + lost-rank watchdog, serve-engine
mid-generation resume, and the `scripts/resume_probe.py` CI gates."""

import json
import os
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.checkpoint import (
    CheckpointManager,
    IncompleteCheckpointError,
    chaos,
    latest_committed_step,
    latest_step,
    load_checkpoint,
    save_checkpoint,
    save_sharded,
    verify_shards,
)
from apex_tpu.checkpoint import sharded as S
from apex_tpu.optimizers.fused_adam import FusedAdam

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run_script(path, *args, timeout=600):
    return subprocess.run(
        [sys.executable, str(path), *args], capture_output=True,
        text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_amp_state_roundtrip():
    state = amp.initialize(opt_level="O2")
    # simulate some scaler evolution
    s = state.loss_scalers[0]
    from apex_tpu.amp import scaler as scaler_lib
    s = scaler_lib.update(s, jnp.asarray(True))   # overflow → halve
    state.loss_scalers[0] = s
    d = amp.state_dict(state)
    assert d["loss_scaler0"]["loss_scale"] == 2.0 ** 15
    state2 = amp.initialize(opt_level="O2")
    state2 = amp.load_state_dict(state2, d)
    assert float(state2.loss_scalers[0].scale) == 2.0 ** 15


def test_checkpoint_roundtrip(tmp_path):
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (4, 3))}
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    _, state = opt.step(state, {"w": jnp.ones((4, 3))})

    path = save_checkpoint(str(tmp_path / "ckpt"), opt.state_dict(state),
                           step=1)
    assert latest_step(str(tmp_path / "ckpt")) == 1
    restored = load_checkpoint(str(tmp_path / "ckpt"), step=1)
    state2 = opt.load_state_dict(restored)
    np.testing.assert_allclose(np.asarray(state2.params),
                               np.asarray(state.params), rtol=1e-6)
    assert int(state2.step) == 1

    # training continues identically from the restored state
    p1, _ = opt.step(state, {"w": jnp.ones((4, 3))})
    p2, _ = opt.step(state2, {"w": jnp.ones((4, 3))})
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                               rtol=1e-6)


def test_flat_layout_guard():
    """A checkpoint written under one flat layout must not restore into
    another (the align=128 offsets differ from the unaligned ones even
    when FLAT_TILE rounding makes the buffer lengths coincide)."""
    import jax
    import jax.numpy as jnp
    import pytest
    from apex_tpu.optimizers import FusedAdam, FusedLAMB

    params = {"w": jnp.ones((300,)), "b": jnp.ones((7,))}
    lamb = FusedLAMB(lr=1e-3)   # align=128 spec
    st = lamb.init(params)
    d = lamb.state_dict(st)
    assert d["flat_layout"]["align"] == 128
    # roundtrip ok
    lamb.load_state_dict(d)
    # missing layout record + aligned spec -> loud failure
    d2 = {k: v for k, v in d.items() if k != "flat_layout"}
    with pytest.raises(ValueError, match="flat_layout"):
        lamb.load_state_dict(d2)
    # mismatched layout -> loud failure
    adam = FusedAdam(lr=1e-3)
    adam.init(params)
    bad = dict(d)
    with pytest.raises(ValueError, match="does not match"):
        adam.load_state_dict(bad)


def test_orbax_missing_messages(tmp_path, monkeypatch):
    """ISSUE 8 satellite: a missing orbax must name the extra — a
    clear warning on the save-side pickle fallback, a clear
    ImportError when an orbax-layout checkpoint can't be read."""
    import sys
    import warnings as _w

    import pytest

    # write a REAL orbax checkpoint first — on an orbax-free install
    # the save silently (correctly) writes pickle and the load-side
    # ImportError assertion below would be a spurious red
    pytest.importorskip("orbax.checkpoint")
    tree = {"w": np.arange(6.0).reshape(2, 3)}
    save_checkpoint(str(tmp_path / "ok"), tree)

    # simulate the uninstalled environment
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        save_checkpoint(str(tmp_path / "fallback"), tree)
    assert any("orbax-checkpoint" in str(r.message) for r in rec)
    # the fallback actually round-trips
    back = load_checkpoint(str(tmp_path / "fallback"))
    np.testing.assert_array_equal(back["w"], tree["w"])

    with pytest.raises(ImportError, match="orbax-checkpoint"):
        load_checkpoint(str(tmp_path / "ok"))


def test_serve_engine_weights_roundtrip(tmp_path):
    """The serve engine's weight pytree (a GPT checkpoint) saves and
    restores through save/load_checkpoint, and the restored weights
    decode IDENTICALLY — the serve-side deployment path (ISSUE 8)."""
    from apex_tpu.models.gpt import GPTConfig
    from apex_tpu.serve import DecodeEngine, ServeConfig

    cfg = GPTConfig(vocab_size=64, seq_len=64, hidden=32, num_layers=2,
                    num_heads=4, dropout=0.0)
    sc = ServeConfig(n_slots=2, max_prompt_len=8, max_new_cap=8,
                     page_size=4)
    from apex_tpu.serve.engine import _init_gpt_params
    params = _init_gpt_params(cfg, seed=3)

    path = save_checkpoint(str(tmp_path / "serve"), params, step=0)
    restored = load_checkpoint(str(tmp_path / "serve"), step=0,
                               target=params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), params, restored)

    eng1 = DecodeEngine(cfg, params, sc)
    eng2 = DecodeEngine(cfg, restored, sc)
    prompt = [5, 11, 3]
    for eng in (eng1, eng2):
        eng.submit(prompt, max_new_tokens=6)
    t1 = eng1.run()[0].tokens
    t2 = eng2.run()[0].tokens
    assert t1 == t2 and len(t1) == 6
    assert path.endswith("step_0")


# ---------------------------------------------------------------------------
# ISSUE 9: the sharded format's commit protocol + validation
# ---------------------------------------------------------------------------

def _toy_sharded(tmp_path, step=7, n=2):
    """A tiny committed 2-rank checkpoint for corruption tests."""
    shards = list(np.split(np.arange(8 * n, dtype=np.float32), n))
    return save_sharded(
        str(tmp_path), step,
        {"params_shard": ("sharded", shards),
         "step": ("replicated", np.asarray(step, np.int32))},
        flat_layout={"align": 1, "total": 8 * n, "n_tensors": 1,
                     "num_shards": n, "n_buckets": 1,
                     "bucket_totals": [8 * n], "bucket_padded": [8 * n],
                     "master_dtype": "float32"})


def test_sharded_commit_and_completeness(tmp_path):
    """The atomicity + validation contract: a manifest-less directory
    is never a checkpoint; a committed one validates; every corruption
    mode (truncated shard, deleted shard, stale manifest, truncated
    manifest) is refused LOUDLY with the damaged ranks named — before
    anything deserializes (the ISSUE 9 satellite)."""
    p = _toy_sharded(tmp_path)
    assert latest_committed_step(str(tmp_path)) == 7
    verify_shards(p)
    # bitwise read-back through the legacy surface too
    host = load_checkpoint(p)
    np.testing.assert_array_equal(
        np.concatenate(host["params_shard"]),
        np.arange(16, dtype=np.float32))
    assert int(host["step"]) == 7

    # truncated shard: named error listing the rank, BEFORE deserialize
    chaos.truncate_shard(p, "params_shard", rank=1)
    with pytest.raises(IncompleteCheckpointError,
                       match="rank 1.*truncated"):
        load_checkpoint(p)
    # ...and the step no longer counts as committed
    assert latest_committed_step(str(tmp_path)) is None

    # deleted shard
    p2 = _toy_sharded(tmp_path / "b")
    chaos.delete_shard(p2, "params_shard", rank=0)
    with pytest.raises(IncompleteCheckpointError, match="rank 0.*missing"):
        verify_shards(p2)

    # stale manifest (references a file that's gone)
    p3 = _toy_sharded(tmp_path / "c")
    chaos.corrupt_manifest(p3, mode="stale")
    with pytest.raises(IncompleteCheckpointError, match="missing"):
        verify_shards(p3)

    # truncated manifest: the COMMIT itself is corrupt
    p4 = _toy_sharded(tmp_path / "d")
    chaos.corrupt_manifest(p4, mode="truncate")
    with pytest.raises(S.CheckpointError, match="not valid JSON"):
        load_checkpoint(p4)

    # crc mismatch at equal size: caught by the checksum sweep
    p5 = _toy_sharded(tmp_path / "e")
    f = os.path.join(p5, "params_shard.rank000.bin")
    raw = bytearray(open(f, "rb").read())
    raw[0] ^= 0xFF
    open(f, "wb").write(bytes(raw))
    with pytest.raises(IncompleteCheckpointError, match="crc32"):
        verify_shards(p5)


def test_kill_mid_save_never_commits(tmp_path):
    """Chaos fail points at every writer stage: the directory left
    behind is NOT loadable, the PREVIOUS commit stays the resume
    point, and an async writer's death surfaces on the training thread
    at wait() (a save that silently failed is a resume point that
    doesn't exist)."""
    opt = FusedAdam(lr=1e-2)
    params = {"w": jnp.ones((300,)), "b": jnp.ones((7,))}
    state = opt.init(params)
    mgr = CheckpointManager(str(tmp_path), opt, every_n_steps=1,
                            async_write=False)
    mgr.save(1, state)
    assert mgr.last_committed_step == 1

    for point in chaos.CKPT_POINTS:  # the single-host writer's points
        with chaos.preempt_at(point):
            with pytest.raises(chaos.SimulatedPreemption):
                mgr.save(2, state)
        # the partial never commits; step 1 remains the resume point
        assert mgr.last_committed_step == 1, point
        with pytest.raises(S.CheckpointError,
                           match="not a committed checkpoint"):
            S.read_manifest(S.step_dir(str(tmp_path), 2))
    # ...and the latest COMMITTED manifest still restores
    restored, _, _ = mgr.restore()
    np.testing.assert_array_equal(np.asarray(restored.params),
                                  np.asarray(state.params))

    # async mode: the writer thread's death re-raises at wait()
    mgr2 = CheckpointManager(str(tmp_path / "async"), opt,
                             every_n_steps=1)
    with chaos.preempt_at("ckpt.before_manifest"):
        mgr2.save(3, state)
        with pytest.raises(chaos.SimulatedPreemption):
            mgr2.wait()
    assert mgr2.last_committed_step is None


def test_overwrite_of_committed_step_is_staged(tmp_path):
    """Re-saving an ALREADY-COMMITTED step must never de-commit it
    mid-write: the new attempt stages in a sibling .tmp dir and swaps
    in only after its own manifest committed, so a kill anywhere
    inside the overwrite leaves the ORIGINAL checkpoint loadable
    (review finding: the old path cleared the manifest first)."""
    p = _toy_sharded(tmp_path, step=5)
    orig = load_checkpoint(p)

    new_fields = {
        "params_shard": ("sharded",
                         list(np.split(np.full(16, 9.0, np.float32), 2))),
        "step": ("replicated", np.asarray(5, np.int32))}
    for point in chaos.CKPT_POINTS:  # the single-host writer's points
        with chaos.preempt_at(point):
            with pytest.raises(chaos.SimulatedPreemption):
                save_sharded(str(tmp_path), 5, new_fields,
                             overwrite=True)
        assert latest_committed_step(str(tmp_path)) == 5, point
        back = load_checkpoint(p)  # the ORIGINAL bytes, every time
        np.testing.assert_array_equal(
            np.concatenate(back["params_shard"]),
            np.concatenate(orig["params_shard"]), err_msg=point)
    # without a kill the overwrite lands and the staging dir is gone
    save_sharded(str(tmp_path), 5, new_fields, overwrite=True)
    np.testing.assert_array_equal(
        np.concatenate(load_checkpoint(p)["params_shard"]),
        np.full(16, 9.0, np.float32))
    assert not os.path.exists(p + ".tmp") and not os.path.exists(
        p + ".old")
    # ...and a committed step without overwrite=True is refused
    with pytest.raises(S.CheckpointError, match="overwrite=True"):
        save_sharded(str(tmp_path), 5, new_fields)


def test_foreign_format_and_target_refused(tmp_path):
    """The sharded writer refuses to clear a step directory holding
    another format's artifacts (a legacy pickle/orbax checkpoint must
    never be silently destroyed as 'aborted partials'), and the legacy
    loader refuses target= on a manifest directory instead of silently
    returning a raw field dict."""
    legacy_dir = save_checkpoint(str(tmp_path), {"w": np.ones(4)},
                                 step=5, use_orbax=False)
    fields = {"step": ("replicated", np.asarray(5, np.int32))}
    with pytest.raises(S.CheckpointError, match="another format"):
        save_sharded(str(tmp_path), 5, fields)
    assert os.path.exists(os.path.join(legacy_dir, "state.pkl"))

    p = _toy_sharded(tmp_path / "sharded")
    with pytest.raises(ValueError, match="restore_sharded"):
        load_checkpoint(p, target={"anything": None})


def test_interrupted_swap_recovers(tmp_path):
    """A kill BETWEEN the overwrite swap's two renames strands the
    step under .old/.tmp names the step scan skips — the discovery
    path must heal it (prefer .tmp: it only commits after the new
    save finished) instead of prune destroying the only copy."""
    import shutil

    p = _toy_sharded(tmp_path, step=5)
    # simulate: old commit displaced to .old, new committed attempt
    # still at .tmp, final name missing
    shutil.move(p, p + ".old")
    new_fields = {
        "params_shard": ("sharded",
                         list(np.split(np.full(16, 9.0, np.float32), 2))),
        "step": ("replicated", np.asarray(5, np.int32))}
    save_sharded(str(tmp_path), 5, new_fields)  # commits at final name
    shutil.move(p, p + ".tmp")
    assert not os.path.exists(p)
    # discovery heals: the .tmp (newer) attempt wins
    assert latest_committed_step(str(tmp_path)) == 5
    np.testing.assert_array_equal(
        np.concatenate(load_checkpoint(p)["params_shard"]),
        np.full(16, 9.0, np.float32))
    # the displaced .old next to a committed final is trash — prune
    # clears it (and never touches the committed step)
    S.prune(str(tmp_path), keep=1)
    assert not os.path.exists(p + ".old")
    assert latest_committed_step(str(tmp_path)) == 5

    # .old alone (staging attempt was invalid/absent): also recovered
    q = _toy_sharded(tmp_path / "b", step=9)
    shutil.move(q, q + ".old")
    assert latest_committed_step(str(tmp_path / "b")) == 9
    assert os.path.exists(q)


def test_restore_falls_back_past_crc_corruption(tmp_path):
    """Size-preserving corruption in the NEWEST commit (the one case
    the cheap commit scan can't see): restore(step=None) warns and
    falls back to the next intact commit instead of aborting a resume
    an older checkpoint could serve; an EXPLICIT step still raises."""
    opt = FusedAdam(lr=1e-2)
    state = opt.init({"w": jnp.ones((128,))})
    mgr = CheckpointManager(str(tmp_path), opt, every_n_steps=1,
                            keep=4, async_write=False)
    mgr.save(4, state)
    mgr.save(8, state)
    # flip one byte of step 8's params at equal size
    f = os.path.join(S.step_dir(str(tmp_path), 8), "params.bin")
    raw = bytearray(open(f, "rb").read())
    raw[0] ^= 0xFF
    open(f, "wb").write(bytes(raw))

    assert latest_committed_step(str(tmp_path)) == 8  # size sweep
    with pytest.warns(UserWarning, match="falling back.*step 4"):
        restored, _, manifest = mgr.restore()
    assert manifest["step"] == 4
    np.testing.assert_array_equal(np.asarray(restored.params),
                                  np.asarray(state.params))
    with pytest.raises(IncompleteCheckpointError, match="crc32"):
        mgr.restore(step=8)


def test_reshard_math_exact():
    """The elastic re-layout is value-exact: dp=2×2-bucket → canonical
    → dp=4×1-bucket → dp=3 and back reproduces the canonical buffer
    bitwise (only zero padding moves), in fp32 AND bf16; incompatible
    layouts (align / total / dtype) are refused."""
    import ml_dtypes

    for dt in (np.float32, ml_dtypes.bfloat16):
        name = np.dtype(dt).name
        canon = np.arange(24).astype(dt)
        src = {"align": 1, "total": 24, "n_tensors": 4, "num_shards": 2,
               "n_buckets": 2, "bucket_totals": [14, 10],
               "bucket_padded": [16, 12], "master_dtype": name}
        shards = list(np.split(S.relayout_flat(canon, src), 2))
        np.testing.assert_array_equal(S.canonical_flat(shards, src),
                                      canon)
        for m, nb in ((4, 1), (3, 3), (1, 2)):
            totals = {1: [24], 2: [14, 10], 3: [8, 8, 8]}[nb]
            dst = {"align": 1, "total": 24, "n_tensors": 4,
                   "num_shards": m, "n_buckets": nb,
                   "bucket_totals": totals,
                   "bucket_padded": [-(-t // m) * m for t in totals],
                   "master_dtype": name}
            g = S.reshard(shards, src, dst)
            np.testing.assert_array_equal(
                S.canonical_flat(list(np.split(g, m)), dst), canon)
    bad = dict(src, align=128)
    with pytest.raises(S.LayoutMismatchError, match="align"):
        S.reshard(shards, src, bad)
    bad = dict(src, master_dtype="float32", total=25)
    with pytest.raises(S.LayoutMismatchError, match="total"):
        S.reshard(shards, dict(src, master_dtype="float32"), bad)


def test_manager_zero2_elastic_restore_bitwise():
    """The manager end-to-end on REAL ZeRO-2 state (dp=2, 2 buckets):
    equal-topology restore is bitwise on every shard buffer, and
    dp=2→dp=1 / dp=2→dp=4 restores carry the SAME canonical values
    (restore moves bytes, not arithmetic — cross-topology value
    equality here is also bitwise; only the training arithmetic after
    resume differs, which scripts/resume_probe.py gates)."""
    import shutil
    import tempfile

    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.parallel import mesh as M
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (300, 4)),
              "b": jax.random.normal(jax.random.PRNGKey(1), (11,))}

    def build(dp):
        M.destroy_model_parallel()
        mesh = M.initialize_model_parallel(devices=jax.devices()[:dp])
        opt = DistributedFusedAdam(num_shards=dp, lr=1e-3, n_buckets=2)
        state = jax.jit(shard_map(
            opt.init, mesh=mesh, in_specs=(P(),),
            out_specs=opt.state_partition_specs(),
            check_vma=False))(params)
        return mesh, opt, state

    mesh2, opt2, state2 = build(2)
    # make the moments non-trivial so bitwise equality has teeth
    g = {"w": jnp.full((300, 4), 1e-3), "b": jnp.full((11,), -2e-3)}
    step_fn = jax.jit(shard_map(
        lambda s, gg: opt2.step(s, gg)[1], mesh=mesh2,
        in_specs=(opt2.state_partition_specs(), P()),
        out_specs=opt2.state_partition_specs(), check_vma=False))
    state2 = step_fn(state2, g)

    tmp = tempfile.mkdtemp()
    try:
        mgr = CheckpointManager(tmp, opt2, every_n_steps=2)
        assert not mgr.maybe_save(3, state2)   # off-cadence
        assert mgr.maybe_save(4, state2)       # on-cadence
        mgr.wait()
        assert mgr.last_committed_step == 4
        st = mgr.stats()
        assert st["ckpt_last_step"] == 4 and st["ckpt_bytes"] > 0
        assert st["ckpt_save_s"] >= 0 and st["ckpt_blocking_s"] >= 0

        # equal topology: bitwise on every buffer
        r2, _, _ = mgr.restore(mesh2)
        for f in state2._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(r2, f)),
                np.asarray(getattr(state2, f)), err_msg=f)

        canon2 = S.canonical_flat(
            list(np.split(np.asarray(state2.params_shard), 2)),
            opt2.shard_layout())
        # elastic: the same values land at dp=1 and dp=4
        for dp in (1, 4):
            meshd, optd, _ = build(dp)
            mgrd = CheckpointManager(tmp, optd)
            rd, _, manifest = mgrd.restore(meshd)
            assert manifest["step"] == 4
            canond = S.canonical_flat(
                list(np.split(np.asarray(rd.params_shard), dp)),
                optd.shard_layout())
            np.testing.assert_array_equal(canond, canon2)
            assert int(np.asarray(rd.step)) == int(
                np.asarray(state2.step))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        M.destroy_model_parallel()


def test_truncated_pickle_named_error(tmp_path):
    """A short pickle (save killed mid-write) names itself instead of
    surfacing an opaque deserialization traceback."""
    import pickle

    d = tmp_path / "pk"
    os.makedirs(d)
    raw = pickle.dumps({"w": np.arange(100.0)})
    with open(d / "state.pkl", "wb") as f:
        f.write(raw[: len(raw) // 2])
    with pytest.raises(S.CheckpointError, match="truncated or corrupt"):
        load_checkpoint(str(d))


def test_metrics_logger_stamps_ckpt_fields(tmp_path):
    """MetricsLogger(ckpt=manager) stamps the v6 ckpt_* cadence-pricing
    scalars into every record once a save committed — and the record
    still validates (OPTIONAL_SCHEMA)."""
    from apex_tpu import monitor
    from apex_tpu.monitor import logger as logger_lib

    opt = FusedAdam(lr=1e-2)
    state = opt.init({"w": jnp.ones((64,))})
    mgr = CheckpointManager(str(tmp_path), opt, every_n_steps=1,
                            async_write=False)

    class _Probe:
        def __init__(self):
            self.records = []

        def write(self, r):
            self.records.append(dict(r))

        def close(self):
            pass

    sink = _Probe()
    logger = monitor.MetricsLogger([sink], ckpt=mgr)
    metrics = monitor.init_metrics()._replace(
        step=jnp.asarray(1, jnp.int32))
    rec = logger.log_step(metrics)
    assert "ckpt_last_step" not in rec          # nothing committed yet
    mgr.maybe_save(1, state)
    metrics = metrics._replace(step=jnp.asarray(2, jnp.int32))
    rec = logger.log_step(metrics)
    assert rec["ckpt_last_step"] == 1
    assert rec["ckpt_bytes"] > 0
    assert rec["ckpt_save_s"] >= 0 and rec["ckpt_blocking_s"] >= 0
    logger_lib.validate_record(rec)


def test_resume_guard_names_last_committed_step(tmp_path):
    """Any exception under chaos.resume_guard dumps a flight report
    whose reason names the last COMMITTED step — the crash artifact IS
    the resume runbook (no recorder schema change)."""
    import json

    from apex_tpu import monitor

    opt = FusedAdam(lr=1e-2)
    state = opt.init({"w": jnp.ones((64,))})
    mgr = CheckpointManager(str(tmp_path / "ck"), opt,
                            every_n_steps=1, async_write=False)
    mgr.save(41, state)
    rec_path = tmp_path / "flight.json"
    recorder = monitor.FlightRecorder(str(rec_path), capacity=4)
    with pytest.raises(RuntimeError, match="boom"):
        with chaos.resume_guard(recorder, mgr):
            recorder.record(41, metrics=None)
            raise RuntimeError("boom")
    rep = json.loads(rec_path.read_text())
    assert "last committed checkpoint: step 41" in rep["reason"]
    from apex_tpu.monitor.trace import report as report_mod
    report_mod.validate_report(rep)  # still schema-valid

    # nothing committed: the guard says so instead of inventing a step
    mgr2 = CheckpointManager(str(tmp_path / "empty"), opt)
    rec2 = tmp_path / "flight2.json"
    recorder2 = monitor.FlightRecorder(str(rec2), capacity=4)
    with pytest.raises(chaos.SimulatedPreemption):
        with chaos.resume_guard(recorder2, mgr2):
            raise chaos.SimulatedPreemption("kill -9")
    assert "NONE COMMITTED" in json.loads(rec2.read_text())["reason"]


def test_lost_rank_watchdog_raises_instead_of_hanging(tmp_path):
    """A persistently slow rank crosses the watchdog deadline and
    raises RankLostError naming the rank, its skew, and the resume
    point — the PR-4 straggler detector escalated from observation to
    fault-tolerance (a hang becomes a crash dump + clean resume)."""
    from apex_tpu.monitor.trace import StragglerDetector

    opt = FusedAdam(lr=1e-2)
    state = opt.init({"w": jnp.ones((64,))})
    mgr = CheckpointManager(str(tmp_path), opt, every_n_steps=1,
                            async_write=False)
    mgr.save(40, state)

    det = StragglerDetector(threshold=1.5, patience=2)
    dog = chaos.LostRankWatchdog(det, manager=mgr, deadline=3)
    base = np.full((4, 2), 0.1)
    for _ in range(2):
        dog.check(base)                 # balanced: no flags
    slow = base.copy()
    slow[2, 0] = 0.5                    # rank 2 goes dark-slow
    dog.check(slow)
    dog.check(slow)                     # flagged (patience 2) < deadline
    with pytest.raises(chaos.RankLostError,
                       match=r"rank 2 .*step 40"):
        dog.check(slow)                 # 3rd consecutive = deadline


def test_serve_engine_preempt_resume_bitwise(tmp_path):
    """ISSUE 9 satellite: a serving node preempted MID-GENERATION
    resumes without numeric drift.  The serve weight pytree AND the
    engine state (paged KV pool, DecodeState, allocator, scheduler
    queues) round-trip through save/load_checkpoint into a FRESH
    engine, and the resumed streams finish with BITWISE the tokens of
    the unpreempted run — whose tokens the PR-8 teacher-forced
    fidelity test already pins to the training forward's argmax."""
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.serve import DecodeEngine, ServeConfig

    cfg = GPTConfig(vocab_size=64, seq_len=64, hidden=32, num_layers=2,
                    num_heads=4, dropout=0.0)
    sc = ServeConfig(n_slots=3, max_prompt_len=8, max_new_cap=8,
                     page_size=4)
    params = GPT(cfg).init(jax.random.PRNGKey(7))
    params["pos_embed"] = params["pos_embed"] * 20.0  # varied decode
    prompts = [[5, 9, 2, 17], [33, 1], [40, 41, 42]]
    budgets = [6, 8, 5]

    # unpreempted reference
    eng1 = DecodeEngine(cfg, params, sc)
    for p, b in zip(prompts, budgets):
        eng1.submit(p, b)
    ref = {f.request_id: f.tokens for f in eng1.run()}
    assert any(len(set(t)) > 1 for t in ref.values()), \
        "degenerate decode — test has no teeth"

    # preempted run: snapshot mid-generation...
    eng2 = DecodeEngine(cfg, params, sc)
    for p, b in zip(prompts, budgets):
        eng2.submit(p, b)
    eng2.step()
    eng2.step()
    snap = eng2.state_dict()
    # pickle format: the snapshot's scheduler queues are plain host
    # containers the orbax pytree layout would mangle on a target-less
    # restore
    path = save_checkpoint(str(tmp_path / "serve"),
                           {"params": params, "engine": snap}, step=2,
                           use_orbax=False)
    half = {f.request_id: f.tokens for f in eng2.poll()}
    del eng2

    # ...and resume into a FRESH engine from the checkpoint
    restored = load_checkpoint(str(tmp_path / "serve"), step=2)
    eng3 = DecodeEngine(cfg, restored["params"], sc)
    eng3.load_state_dict(restored["engine"])
    finished = dict(half)
    finished.update(
        {f.request_id: f.tokens for f in eng3.run()})
    assert finished == ref
    assert eng3.recompile_ok
    assert eng3.cache.free_pages == eng1.cache.free_pages

    # a snapshot from a DIFFERENT deployment is refused loudly
    other = DecodeEngine(cfg, params, ServeConfig(
        n_slots=2, max_prompt_len=8, max_new_cap=8, page_size=4))
    with pytest.raises(ValueError, match="different deployment"):
        other.load_state_dict(snap)


def test_resume_probe_selftest():
    """Tier-1 CI gate (mirrors lint_step/comms_probe/flight_report
    --selftest): the committed manifest fixture still validates, the
    reshard math round-trips bitwise, and the seeded truncated shard
    is refused with its rank named."""
    r = _run_script(ROOT / "scripts" / "resume_probe.py", "--selftest")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "resume_probe --selftest: OK" in r.stdout


def test_resume_probe_full_gate():
    """The standing save→kill→restore→trajectory-match gate (ISSUE 9
    acceptance): kill-mid-save leaves the last committed manifest
    restorable, equal-topology preempt/resume reproduces the
    unpreempted loss trajectory BITWISE, dp=2→dp=1 and dp=2→dp=4
    resumes match allclose, and every resumed run shows zero
    steady-state recompiles (RecompileSentry-enforced)."""
    r = _run_script(ROOT / "scripts" / "resume_probe.py",
                    "--steps", "6", "--save-at", "3", "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    payload = None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and "ok" in d:
            payload = d
            break
    assert payload is not None, r.stdout
    assert payload["ok"] is True
    assert payload["equal_topology_bitwise"] is True
    assert payload["dp1_allclose"] is True
    assert payload["dp4_allclose"] is True
    assert payload["last_committed_after_kill"] == 3
