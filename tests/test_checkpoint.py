"""Checkpoint/resume ≡ tests/L0/run_amp/test_checkpointing.py: scaler
state round-trips, optimizer/model state round-trips, auto-resume."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.checkpoint import latest_step, load_checkpoint, save_checkpoint
from apex_tpu.optimizers.fused_adam import FusedAdam


def test_amp_state_roundtrip():
    state = amp.initialize(opt_level="O2")
    # simulate some scaler evolution
    s = state.loss_scalers[0]
    from apex_tpu.amp import scaler as scaler_lib
    s = scaler_lib.update(s, jnp.asarray(True))   # overflow → halve
    state.loss_scalers[0] = s
    d = amp.state_dict(state)
    assert d["loss_scaler0"]["loss_scale"] == 2.0 ** 15
    state2 = amp.initialize(opt_level="O2")
    state2 = amp.load_state_dict(state2, d)
    assert float(state2.loss_scalers[0].scale) == 2.0 ** 15


def test_checkpoint_roundtrip(tmp_path):
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (4, 3))}
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    _, state = opt.step(state, {"w": jnp.ones((4, 3))})

    path = save_checkpoint(str(tmp_path / "ckpt"), opt.state_dict(state),
                           step=1)
    assert latest_step(str(tmp_path / "ckpt")) == 1
    restored = load_checkpoint(str(tmp_path / "ckpt"), step=1)
    state2 = opt.load_state_dict(restored)
    np.testing.assert_allclose(np.asarray(state2.params),
                               np.asarray(state.params), rtol=1e-6)
    assert int(state2.step) == 1

    # training continues identically from the restored state
    p1, _ = opt.step(state, {"w": jnp.ones((4, 3))})
    p2, _ = opt.step(state2, {"w": jnp.ones((4, 3))})
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                               rtol=1e-6)


def test_flat_layout_guard():
    """A checkpoint written under one flat layout must not restore into
    another (the align=128 offsets differ from the unaligned ones even
    when FLAT_TILE rounding makes the buffer lengths coincide)."""
    import jax
    import jax.numpy as jnp
    import pytest
    from apex_tpu.optimizers import FusedAdam, FusedLAMB

    params = {"w": jnp.ones((300,)), "b": jnp.ones((7,))}
    lamb = FusedLAMB(lr=1e-3)   # align=128 spec
    st = lamb.init(params)
    d = lamb.state_dict(st)
    assert d["flat_layout"]["align"] == 128
    # roundtrip ok
    lamb.load_state_dict(d)
    # missing layout record + aligned spec -> loud failure
    d2 = {k: v for k, v in d.items() if k != "flat_layout"}
    with pytest.raises(ValueError, match="flat_layout"):
        lamb.load_state_dict(d2)
    # mismatched layout -> loud failure
    adam = FusedAdam(lr=1e-3)
    adam.init(params)
    bad = dict(d)
    with pytest.raises(ValueError, match="does not match"):
        adam.load_state_dict(bad)


def test_orbax_missing_messages(tmp_path, monkeypatch):
    """ISSUE 8 satellite: a missing orbax must name the extra — a
    clear warning on the save-side pickle fallback, a clear
    ImportError when an orbax-layout checkpoint can't be read."""
    import sys
    import warnings as _w

    import pytest

    # write a REAL orbax checkpoint first — on an orbax-free install
    # the save silently (correctly) writes pickle and the load-side
    # ImportError assertion below would be a spurious red
    pytest.importorskip("orbax.checkpoint")
    tree = {"w": np.arange(6.0).reshape(2, 3)}
    save_checkpoint(str(tmp_path / "ok"), tree)

    # simulate the uninstalled environment
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        save_checkpoint(str(tmp_path / "fallback"), tree)
    assert any("orbax-checkpoint" in str(r.message) for r in rec)
    # the fallback actually round-trips
    back = load_checkpoint(str(tmp_path / "fallback"))
    np.testing.assert_array_equal(back["w"], tree["w"])

    with pytest.raises(ImportError, match="orbax-checkpoint"):
        load_checkpoint(str(tmp_path / "ok"))


def test_serve_engine_weights_roundtrip(tmp_path):
    """The serve engine's weight pytree (a GPT checkpoint) saves and
    restores through save/load_checkpoint, and the restored weights
    decode IDENTICALLY — the serve-side deployment path (ISSUE 8)."""
    from apex_tpu.models.gpt import GPTConfig
    from apex_tpu.serve import DecodeEngine, ServeConfig

    cfg = GPTConfig(vocab_size=64, seq_len=64, hidden=32, num_layers=2,
                    num_heads=4, dropout=0.0)
    sc = ServeConfig(n_slots=2, max_prompt_len=8, max_new_cap=8,
                     page_size=4)
    from apex_tpu.serve.engine import _init_gpt_params
    params = _init_gpt_params(cfg, seed=3)

    path = save_checkpoint(str(tmp_path / "serve"), params, step=0)
    restored = load_checkpoint(str(tmp_path / "serve"), step=0,
                               target=params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), params, restored)

    eng1 = DecodeEngine(cfg, params, sc)
    eng2 = DecodeEngine(cfg, restored, sc)
    prompt = [5, 11, 3]
    for eng in (eng1, eng2):
        eng.submit(prompt, max_new_tokens=6)
    t1 = eng1.run()[0].tokens
    t2 = eng2.run()[0].tokens
    assert t1 == t2 and len(t1) == 6
    assert path.endswith("step_0")
