"""Spatial-parallel conv + groupbn + peer halo + misc contrib facades.
≡ apex/contrib/test/{bottleneck,peer_memory,conv_bias_relu} tests."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.bottleneck import spatial_conv2d
from apex_tpu.contrib.conv_bias_relu import conv_bias_relu
from apex_tpu.contrib.fmha import FMHA
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.peer_memory import PeerHaloExchanger1d
from apex_tpu.models.resnet import conv2d
from apex_tpu.parallel import mesh as M


def test_spatial_conv_matches_dense():
    """H-sharded 3x3 conv with halo exchange == unsharded SAME conv
    (≡ test_peer_halo_exchange_module.py / SpatialBottleneck parity)."""
    mesh = M.initialize_model_parallel()  # dp=8
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 8, 3))
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, 5)) * 0.2

    f = shard_map(
        lambda xl, w: spatial_conv2d(xl, w, "dp"),
        mesh=mesh, in_specs=(P(None, "dp"), P()),
        out_specs=P(None, "dp"), check_vma=False)
    got = f(x, w)
    want = conv2d(x, w, padding="SAME")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_spatial_conv_grads():
    mesh = M.initialize_model_parallel()
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 4, 2))
    w = jax.random.normal(jax.random.PRNGKey(3), (3, 3, 2, 2)) * 0.3

    def local_grads(xl, w):
        def loss(xl, w):
            return jnp.sum(spatial_conv2d(xl, w, "dp") ** 2)
        return jax.grad(loss, argnums=(0, 1))(xl, w)

    gx, gw = shard_map(local_grads, mesh=mesh,
                       in_specs=(P(None, "dp"), P()),
                       out_specs=(P(None, "dp"), P()),
                       check_vma=False)(x, w)
    rx, rw = jax.grad(
        lambda xl, w: jnp.sum(conv2d(xl, w, padding="SAME") ** 2),
        argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-4,
                               atol=1e-4)
    # w grad partial per rank; psum'd by the custom_vjp? No — w is
    # replicated input and each rank computed its H slice: the global
    # grad is the SUM over ranks; out_specs P() takes rank 0's partial.
    # Compare the summed version instead:
    def local_grads_sum(xl, w):
        def loss(xl, w):
            return jnp.sum(spatial_conv2d(xl, w, "dp") ** 2)
        gx, gw = jax.grad(loss, argnums=(0, 1))(xl, w)
        return gx, jax.lax.psum(gw, "dp")

    _, gw2 = shard_map(local_grads_sum, mesh=mesh,
                       in_specs=(P(None, "dp"), P()),
                       out_specs=(P(None, "dp"), P()),
                       check_vma=False)(x, w)
    np.testing.assert_allclose(np.asarray(gw2), np.asarray(rw), rtol=1e-4,
                               atol=1e-3)


def test_groupbn_subgroup():
    """bn_group=4 over a factored mesh: stats merge within each group of
    4 only (≡ groupbn IPC subgroups / syncbn process_group tests)."""
    import numpy as onp
    from jax.sharding import Mesh
    devs = onp.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dpo", "bn"))
    x = jax.random.normal(jax.random.PRNGKey(4), (16, 2, 2, 6))
    bn = BatchNorm2d_NHWC(6, axis_name="bn", bn_group=4)
    params, state = bn.init()

    def local(xl):
        y, _ = bn.apply(params, state, xl, training=True)
        return y

    f = shard_map(local, mesh=mesh, in_specs=P(("dpo", "bn")),
                  out_specs=P(("dpo", "bn")), check_vma=False)
    got = np.asarray(f(x))
    # reference: normalize each half (8 samples) independently
    for half in range(2):
        xs = np.asarray(x[half * 8:(half + 1) * 8])
        mean = xs.mean(axis=(0, 1, 2))
        var = xs.var(axis=(0, 1, 2))
        want = (xs - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(got[half * 8:(half + 1) * 8], want,
                                   rtol=1e-3, atol=1e-3)


def test_peer_halo_exchanger():
    mesh = M.initialize_model_parallel()
    y = jnp.arange(64.0).reshape(1, 64, 1, 1)
    ex = PeerHaloExchanger1d(half_halo=1, axis_name="dp")

    f = shard_map(lambda yl: ex(yl)[0], mesh=mesh,
                  in_specs=P(None, "dp"), out_specs=P(None, "dp"),
                  check_vma=False)
    left = np.asarray(f(y)).ravel()
    # rank r receives prev rank's last row: y[8r-1 mod 64]
    expect = [(8 * r - 1) % 64 for r in range(8)]
    np.testing.assert_allclose(left, expect)


def test_conv_bias_relu_and_fmha():
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 8, 8, 3))
    w = jax.random.normal(jax.random.PRNGKey(6), (3, 3, 3, 4)) * 0.2
    b = jnp.linspace(-1, 1, 4)
    y = conv_bias_relu(x, w, b)
    want = np.maximum(np.asarray(conv2d(x, w)) + np.asarray(b), 0)
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5, atol=1e-5)
    assert (np.asarray(y) >= 0).all()

    qkv = jax.random.normal(jax.random.PRNGKey(7), (2, 32, 3, 4, 16))
    out = FMHA(causal=True)(qkv)
    assert out.shape == (2, 32, 4, 16)
