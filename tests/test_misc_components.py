"""Tests for ASP sparsity, RNN zoo, batch samplers, FP16_Optimizer,
MP grad scaler, timers, testing commons, argument parser.
≡ the reference's scattered unit tests for these (contrib/test/,
tests/L0/run_transformer/test_batch_sampler.py, test_fp16_optimizer
paths)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp.fp16_optimizer import FP16_Optimizer
from apex_tpu.contrib.sparsity import ASP, create_mask
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.rnn import GRU, LSTM, RNNTanh, mLSTM
from apex_tpu.transformer._data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)
from apex_tpu.transformer.testing.arguments import parse_args
from apex_tpu.transformer.testing.commons import (
    MyModel,
    ToyParallelMLP,
    set_random_seed,
)
from apex_tpu.utils.timers import Timers


def test_create_mask_2to4():
    w = jax.random.normal(jax.random.PRNGKey(0), (8, 16))
    mask = create_mask(w)
    m = np.asarray(mask).reshape(-1, 4)
    assert (m.sum(axis=1) == 2).all()  # exactly 2 of every 4 kept
    # kept entries are the top-2 magnitudes in each group
    flat = np.abs(np.asarray(w)).reshape(-1, 4)
    for row, mk in zip(flat, m):
        kept = set(np.where(mk == 1)[0])
        top2 = set(np.argsort(row)[-2:])
        assert kept == top2


def test_asp_workflow():
    params = {"layer": {"weight": jax.random.normal(
        jax.random.PRNGKey(1), (16, 8)), "bias": jnp.ones((8,))}}
    asp = ASP()
    sparse = asp.init_model_for_pruning(params)
    assert abs(asp.sparsity(sparse) - 0.5) < 1e-6
    # bias untouched
    np.testing.assert_allclose(np.asarray(sparse["layer"]["bias"]), 1.0)
    # simulate optimizer step then re-apply
    updated = jax.tree_util.tree_map(lambda x: x + 0.1, sparse)
    masked = asp.apply(updated)
    w = np.asarray(masked["layer"]["weight"]).reshape(-1, 4)
    assert ((w != 0).sum(axis=1) <= 2).all()


@pytest.mark.parametrize("cls", [RNNTanh, LSTM, GRU, mLSTM])
def test_rnn_cells(cls):
    rnn = cls(6, 10, num_layers=2)
    params = rnn.init(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (5, 3, 6))
    y = rnn.apply(params, x)
    assert y.shape == (5, 3, 10)
    g = jax.grad(lambda p: jnp.sum(rnn.apply(p, x) ** 2))(params)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(g))


def test_rnn_bidirectional():
    rnn = LSTM(4, 8, bidirectional=True)
    params = rnn.init(jax.random.PRNGKey(4))
    x = jax.random.normal(jax.random.PRNGKey(5), (6, 2, 4))
    y = rnn.apply(params, x)
    assert y.shape == (6, 2, 16)


def test_pretraining_sampler():
    s = MegatronPretrainingSampler(
        total_samples=32, consumed_samples=0, micro_batch_size=2,
        data_parallel_rank=1, data_parallel_size=4)
    batches = list(s)
    assert batches[0] == [2, 3]  # rank 1's slice of the first batch of 8
    assert batches[1] == [10, 11]
    assert len(batches) == 4


def test_pretraining_random_sampler():
    a = list(MegatronPretrainingRandomSampler(
        total_samples=32, consumed_samples=0, micro_batch_size=2,
        data_parallel_rank=0, data_parallel_size=4))
    b = list(MegatronPretrainingRandomSampler(
        total_samples=32, consumed_samples=0, micro_batch_size=2,
        data_parallel_rank=0, data_parallel_size=4))
    assert a == b  # epoch-seeded determinism
    flat = [i for batch in a for i in batch]
    assert len(set(flat)) == len(flat)
    assert all(0 <= i < 8 for i in flat)  # rank-0 bucket


def test_fp16_optimizer_workflow():
    params = {"w": jnp.ones((4,))}
    opt = FP16_Optimizer(FusedSGD(lr=0.1, use_pallas=False),
                         dynamic_loss_scale=True)
    state = opt.init(params)
    scale0 = opt.loss_scale
    assert scale0 == 2.0 ** 16
    grads = {"w": jnp.full((4,), 0.5) * scale0}  # pre-scaled grads
    new_params, state = opt.step(state, grads)
    np.testing.assert_allclose(np.asarray(new_params["w"]), 1.0 - 0.05,
                               rtol=1e-5)
    # overflow path: inf grads → params unchanged, scale halves
    bad = {"w": jnp.full((4,), jnp.inf)}
    p2, state = opt.step(state, bad)
    np.testing.assert_allclose(np.asarray(p2["w"]),
                               np.asarray(new_params["w"]))
    assert opt.loss_scale == scale0 / 2


def test_mp_grad_scaler():
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.amp.grad_scaler import allreduce_found_inf

    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)

    def local(flag):
        return allreduce_found_inf(flag[0], axis_names=("tp",))

    # only rank 3 overflows → every rank must report True
    flags = jnp.zeros((8, 1), bool).at[3].set(True)
    f = shard_map(local, mesh=mesh, in_specs=P("tp"), out_specs=P("tp"),
                  check_vma=False)
    out = np.asarray(f(flags.astype(jnp.float32)))
    assert out.all()
    M.destroy_model_parallel()


def test_timers_and_commons_and_args():
    t = Timers()
    t("fwd").start()
    t("fwd").stop()
    assert "fwd" in t.log(["fwd"])

    key = set_random_seed(123)
    model = MyModel(8, num_layers=3)
    p = model.init(key)
    y = model.apply(p, jnp.ones((2, 8)))
    assert y.shape == (2, 8)

    args = parse_args(ignore_unknown_args=True, defaults={
        "num_layers": 2, "hidden_size": 64, "num_attention_heads": 4})
    assert args.tensor_model_parallel_size == 1
    assert args.hidden_size == 64
    assert args.kv_channels == 16


def test_get_batch_per_block():
    from apex_tpu.ops.softmax import get_batch_per_block
    # parity shim for scaled_masked_softmax_cuda.get_batch_per_block
    assert get_batch_per_block(128, 128, 4, 8) >= 1
    assert isinstance(get_batch_per_block(2048, 2048, 1, 1), int)


def test_future_tensor():
    import jax.numpy as jnp
    from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
        FutureTensor)
    f = FutureTensor(jnp.arange(3.0))
    assert float(f.get()[1]) == 1.0
    assert f.tensor.shape == (3,)


def test_new_process_group_axes():
    import pytest
    from apex_tpu.parallel import mesh as M
    M.destroy_model_parallel()
    M.initialize_model_parallel(tensor_model_parallel_size=2)
    assert M.new_process_group("tp") == ("tp",)
    assert M.new_process_group(["dp", "tp"]) == ("dp", "tp")
    with pytest.raises(ValueError):
        M.new_process_group("ep")
    M.destroy_model_parallel()


def test_distributed_saved_activation_checkpoint_grads():
    """The tp-sharded residual checkpoint must be gradient-exact vs the
    plain function (≡ CheckpointFunction distribute_saved_activations,
    random.py:237-306)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.tensor_parallel.random import (
        checkpoint_with_distributed_saved_activations)

    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    w = jax.random.normal(jax.random.PRNGKey(0), (16, 16))
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 16))

    def fn(x, w):
        return jnp.sum(jnp.tanh(x @ w) ** 2)

    ck = checkpoint_with_distributed_saved_activations(fn)

    def loss_plain(x, w):
        return fn(x, w)

    def loss_ck(x, w):
        return ck(x, w)

    gp = shard_map(jax.grad(loss_plain, argnums=(0, 1)), mesh=mesh,
                   in_specs=(P(), P()), out_specs=(P(), P()),
                   check_vma=False)(x, w)
    gc = shard_map(jax.grad(loss_ck, argnums=(0, 1)), mesh=mesh,
                   in_specs=(P(), P()), out_specs=(P(), P()),
                   check_vma=False)(x, w)
    for a, b in zip(gp, gc):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    M.destroy_model_parallel()


# ---------------- ASP stripe-group permutation search (round 2) -------------

def _random_swap_search(w, num_iters=100, seed=0):
    """The round-1 baseline this search must beat: random column swaps."""
    import numpy as np
    from apex_tpu.contrib.sparsity import magnitude_after_mask
    c = w.shape[-1]
    perm = np.arange(c)
    best = float(magnitude_after_mask(jnp.asarray(w[:, perm])))
    rng = np.random.RandomState(seed)
    for _ in range(num_iters):
        i, j = rng.randint(0, c, 2)
        if i == j:
            continue
        cand = perm.copy()
        cand[i], cand[j] = cand[j], cand[i]
        s = float(magnitude_after_mask(jnp.asarray(w[:, cand])))
        if s > best:
            best, perm = s, cand
    return perm, best


def test_permutation_search_reaches_known_optimum():
    """Known structure: four big columns packed into one stripe — 2:4
    keeps only two of them under identity; the optimal permutation
    spreads them two per stripe and retains everything."""
    import numpy as np
    from apex_tpu.contrib.sparsity import (
        magnitude_after_mask, search_channel_permutation)
    w = np.ones((16, 8), np.float32) * 0.1
    w[:, :4] = 5.0
    perm, score = search_channel_permutation(w)
    assert sorted(perm.tolist()) == list(range(8))
    ident = float(magnitude_after_mask(jnp.asarray(w)))
    # optimum keeps all four big columns (2 per stripe); the 0.1s lose
    optimum = 16 * 4 * 5.0
    np.testing.assert_allclose(score, optimum, rtol=1e-5)
    assert score > ident * 1.5


def test_permutation_search_beats_random_swap():
    import numpy as np
    from apex_tpu.contrib.sparsity import search_channel_permutation
    rng = np.random.RandomState(3)
    # heavy-tailed columns make grouping matter
    w = (rng.randn(32, 64) * (rng.rand(64) ** 4 * 10 + 0.1)).astype(
        np.float32)
    _, s_stripe = search_channel_permutation(w, escape_attempts=4)
    _, s_swap = _random_swap_search(w, num_iters=100)
    assert s_stripe > s_swap, (s_stripe, s_swap)


def test_permutation_search_subdivides_wide_matrices():
    import numpy as np
    from apex_tpu.contrib.sparsity import (
        magnitude_after_mask, search_channel_permutation)
    rng = np.random.RandomState(4)
    w = (rng.randn(8, 1024) * (rng.rand(1024) ** 3 * 5 + 0.1)).astype(
        np.float32)
    perm, score = search_channel_permutation(w, escape_attempts=0,
                                             max_cols=256)
    assert sorted(perm.tolist()) == list(range(1024))
    ident = float(magnitude_after_mask(jnp.asarray(w)))
    assert score >= ident
