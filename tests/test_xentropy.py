"""Fused cross-entropy parity ≡ apex/contrib/test/xentropy tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.xentropy import (
    softmax_cross_entropy_loss,
    softmax_cross_entropy_reference,
)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
@pytest.mark.parametrize("shape", [(8, 32), (3, 5, 17)])
def test_xent_forward(shape, smoothing):
    x = jax.random.normal(jax.random.PRNGKey(0), shape) * 3
    labels = jax.random.randint(jax.random.PRNGKey(1), shape[:-1], 0,
                                shape[-1])
    got = softmax_cross_entropy_loss(x, labels, smoothing,
                                     use_pallas_override=True)
    want = softmax_cross_entropy_reference(x, labels, smoothing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xent_grad(smoothing):
    x = jax.random.normal(jax.random.PRNGKey(2), (16, 50)) * 2
    labels = jax.random.randint(jax.random.PRNGKey(3), (16,), 0, 50)

    g1 = jax.grad(lambda a: jnp.mean(softmax_cross_entropy_loss(
        a, labels, smoothing, use_pallas_override=True)))(x)
    g2 = jax.grad(lambda a: jnp.mean(softmax_cross_entropy_reference(
        a, labels, smoothing)))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)

    # analytic: dx = (softmax - q)/N
    p = jax.nn.softmax(x, axis=-1)
    q = (1 - smoothing) * jax.nn.one_hot(labels, 50) + smoothing / 50
    np.testing.assert_allclose(np.asarray(g1), np.asarray((p - q) / 16),
                               rtol=1e-4, atol=1e-6)
