"""Fused dense / MLP parity ≡ tests/L0/run_mlp/test_mlp.py and
fused-dense tests: Pallas matmul+epilogue (interpret on CPU) vs jnp."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.fused_dense import (
    FusedDense,
    FusedDenseGeluDense,
    linear_bias,
    linear_bias_reference,
    linear_gelu_linear,
    wgrad_accum,
)
from apex_tpu.ops.mlp import MLP, mlp_forward


@pytest.mark.parametrize("act", [None, "relu", "gelu", "sigmoid"])
@pytest.mark.parametrize("shape", [(8, 16, 32), (130, 70, 50)])
def test_linear_bias_forward(act, shape):
    m, k, n = shape
    x = jax.random.normal(jax.random.PRNGKey(0), (m, k))
    w = jax.random.normal(jax.random.PRNGKey(1), (k, n)) * 0.1
    b = jax.random.normal(jax.random.PRNGKey(2), (n,))
    got = linear_bias(x, w, b, act, use_pallas_override=True)
    want = linear_bias_reference(x, w, b, act)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("act", [None, "relu", "gelu"])
def test_linear_bias_grads(act):
    x = jax.random.normal(jax.random.PRNGKey(3), (12, 24))
    w = jax.random.normal(jax.random.PRNGKey(4), (24, 16)) * 0.2
    b = jnp.zeros((16,))

    def loss_p(x, w, b):
        return jnp.sum(jnp.sin(linear_bias(x, w, b, act,
                                           use_pallas_override=True)))

    def loss_r(x, w, b):
        return jnp.sum(jnp.sin(linear_bias_reference(x, w, b, act)))

    g1 = jax.grad(loss_p, argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(loss_r, argnums=(0, 1, 2))(x, w, b)
    for a, e in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-4)


def test_linear_gelu_linear():
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 8, 16))
    mod = FusedDenseGeluDense(16, 32, 8)
    p = mod.init(jax.random.PRNGKey(6))
    got = mod.apply(p, x, use_pallas_override=True)
    h = linear_bias_reference(x, p["weight1"], p["bias1"], "gelu")
    want = linear_bias_reference(h, p["weight2"], p["bias2"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_mlp_vs_sequential():
    """≡ tests/L0/run_mlp/test_mlp.py: MLP vs explicit layer chain."""
    mlp = MLP([13, 27, 11, 5], activation="relu")
    p = mlp.init(jax.random.PRNGKey(7))
    x = jax.random.normal(jax.random.PRNGKey(8), (9, 13))
    got = mlp.apply(p, x, use_pallas_override=True)
    h = x
    for i, (w, b) in enumerate(zip(p["weights"], p["biases"])):
        h = h @ w + b
        if i < 2:
            h = jnp.maximum(h, 0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(h),
                               rtol=1e-4, atol=1e-4)

    # grads flow through the whole chain
    g = jax.grad(lambda pp: jnp.sum(
        mlp.apply(pp, x, use_pallas_override=True) ** 2))(p)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(g))


def test_wgrad_accum():
    main = jnp.ones((6, 4), jnp.float32) * 0.5
    x = jax.random.normal(jax.random.PRNGKey(9), (10, 6))
    g = jax.random.normal(jax.random.PRNGKey(10), (10, 4))
    out = wgrad_accum(main, x, g)
    np.testing.assert_allclose(np.asarray(out),
                               0.5 + np.asarray(x).T @ np.asarray(g),
                               rtol=1e-5, atol=1e-5)
