"""Data-parallel layer tests ≡ tests/distributed/DDP + amp_master_params:
grad sync correctness (analytic), bucketed == unbucketed, and the fused
train step trains a model identically to single-device full-batch SGD.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M
from apex_tpu.parallel.clip_grad import clip_grad_norm
from apex_tpu.parallel.larc import LARC


def test_sync_gradients_analytic():
    """≡ ddp_race_condition_test.py:44-62 — analytically known grads."""
    mesh = M.initialize_model_parallel()
    g = jnp.arange(8.0).reshape(8, 1)  # rank r holds value r

    f = shard_map(lambda x: ddp.sync_gradients({"g": x}, "dp")["g"],
                  mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                  check_vma=False)
    out = f(g)
    np.testing.assert_allclose(np.asarray(out), 3.5)  # mean(0..7)


def test_bucketed_matches_plain():
    mesh = M.initialize_model_parallel()
    tree = {"a": jnp.arange(24.0).reshape(8, 3),
            "b": jnp.arange(8.0).reshape(8, 1) * 2}

    def plain(t):
        return ddp.sync_gradients(t, "dp")

    def bucketed(t):
        return ddp.sync_gradients_bucketed(t, "dp", num_buckets=2)

    f1 = shard_map(plain, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                   check_vma=False)
    f2 = shard_map(bucketed, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                   check_vma=False)
    o1, o2 = f1(tree), f2(tree)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b), rtol=1e-6),
        o1, o2)


def test_make_train_step_matches_full_batch():
    mesh = M.initialize_model_parallel()  # dp=8
    w_true = jnp.array([[2.0], [-3.0]])
    # numpy RNG: jax.random output differs across jax versions, and the
    # 10-step convergence margin below is data-dependent
    X = jnp.asarray(np.random.default_rng(3).normal(size=(32, 2)),
                    jnp.float32)
    Y = X @ w_true

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2)

    params0 = {"w": jnp.zeros((2, 1))}

    # sharded training
    opt = FusedSGD(lr=0.1, use_pallas=False)
    state = opt.init(params0)
    step = ddp.make_train_step(loss_fn, opt, mesh,
                               batch_spec=(P("dp"), P("dp")))
    losses = []
    for _ in range(10):
        state, _, loss = step(state, None, (X, Y))
        losses.append(float(loss))

    # single-device full-batch reference
    opt2 = FusedSGD(lr=0.1, use_pallas=False)
    state2 = opt2.init(params0)
    for _ in range(10):
        grads = jax.grad(loss_fn)(
            __import__("apex_tpu.optimizers.flat", fromlist=["unflatten"])
            .unflatten(state2.params, opt2.spec), (X, Y))
        _, state2 = opt2.step(state2, grads)

    np.testing.assert_allclose(np.asarray(state.params),
                               np.asarray(state2.params), rtol=1e-5,
                               atol=1e-6)
    assert losses[-1] < losses[0] * 0.1


def test_make_train_step_with_amp_dynamic_scaling():
    mesh = M.initialize_model_parallel()
    X = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
    Y = jnp.sum(X, axis=1, keepdims=True)

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y) ** 2)

    params0 = {"w": jnp.zeros((4, 1))}
    opt = FusedSGD(lr=0.05, use_pallas=False)
    state = opt.init(params0)
    amp_state = amp.initialize(opt_level="O1")
    scaler_state = amp_state.loss_scalers[0]
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")))
    losses = []
    for _ in range(15):
        state, scaler_state, loss = step(state, scaler_state, (X, Y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5
    assert float(scaler_state.scale) == 2.0 ** 16  # no overflow happened


def test_clip_grad_norm():
    grads = {"a": jnp.full((10,), 3.0), "b": jnp.full((6,), 4.0)}
    clipped, total = clip_grad_norm(grads, max_norm=1.0)
    expect_total = np.sqrt(10 * 9 + 6 * 16)
    np.testing.assert_allclose(float(total), expect_total, rtol=1e-5)
    flat = np.concatenate([np.asarray(clipped["a"]),
                           np.asarray(clipped["b"])])
    np.testing.assert_allclose(np.linalg.norm(flat), 1.0, rtol=1e-4)
    # no-op below threshold
    c2, _ = clip_grad_norm(grads, max_norm=1e9)
    np.testing.assert_allclose(np.asarray(c2["a"]), 3.0)


def test_larc_clip_mode():
    params = {"w": jnp.full((4,), 2.0)}
    opt = FusedSGD(lr=0.1, use_pallas=False)
    larc = LARC(opt, trust_coefficient=0.02, clip=True)
    state = larc.init(params)
    grads = {"w": jnp.full((4,), 1.0)}
    new_params, _ = larc.step(state, grads)
    # local_lr = 0.02*||p||/||g|| = 0.02*4/2 = 0.04 < lr → scale=0.04/0.1
    expect = 2.0 - 0.1 * (0.04 / 0.1) * 1.0
    np.testing.assert_allclose(np.asarray(new_params["w"]), expect,
                               rtol=1e-5)


def test_make_train_step_zero2_matches_fused_adam():
    """ISSUE 3 satellite: DistributedFusedAdam wired through
    ddp.make_train_step on a 2-shard dp mesh must train identically to
    single-device full-batch FusedAdam — for both n_buckets=1 and the
    backward-overlap n_buckets=2 layout."""
    from apex_tpu.optimizers import flat as F
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.optimizers.fused_adam import FusedAdam

    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])  # dp=2
    w_true = jnp.array([[2.0], [-3.0]])
    X = jnp.asarray(np.random.default_rng(7).normal(size=(32, 2)),
                    jnp.float32)
    Y = X @ w_true

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)

    params0 = {"w": jnp.zeros((2, 1)), "b": jnp.zeros((1,))}

    # single-device full-batch FusedAdam reference
    ref_opt = FusedAdam(lr=1e-2, weight_decay=0.01, use_pallas=False)
    ref_state = ref_opt.init(params0)
    losses_ref = []
    for _ in range(5):
        p = F.unflatten(ref_state.params, ref_opt.spec)
        losses_ref.append(float(loss_fn(p, (X, Y))))
        g = jax.grad(loss_fn)(p, (X, Y))
        _, ref_state = ref_opt.step(ref_state, g)
    p_ref = F.unflatten(ref_state.params, ref_opt.spec)

    for nb in (1, 2):
        opt = DistributedFusedAdam(num_shards=2, lr=1e-2,
                                   weight_decay=0.01, use_pallas=False,
                                   n_buckets=nb)
        sspec = opt.state_partition_specs()
        # fresh optimizer per bucket config: the per-iteration init
        # jit is inherent to the sweep, not a retrace leak
        state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),  # lint: disable=HS405
                                  out_specs=sspec,
                                  check_vma=False))(params0)
        step = ddp.make_train_step(loss_fn, opt, mesh,
                                   batch_spec=(P("dp"), P("dp")))
        losses = []
        for _ in range(5):
            state, _, loss = step(state, None, (X, Y))
            losses.append(float(loss))
        gather = jax.jit(shard_map(  # lint: disable=HS405
            lambda s: opt.full_params(s), mesh=mesh, in_specs=(sspec,),
            out_specs=P(), check_vma=False))
        p_z = gather(state)
        for leaf_z, leaf_r in zip(jax.tree_util.tree_leaves(p_z),
                                  jax.tree_util.tree_leaves(p_ref)):
            np.testing.assert_allclose(np.asarray(leaf_z),
                                       np.asarray(leaf_r),
                                       rtol=1e-5, atol=1e-6)
        # the step's loss output is the shard-local pre-update loss;
        # exact parity is asserted on the params above — just require
        # the trajectory to be improving
        assert losses[-1] < losses[0]
        assert int(jax.device_get(state.step)) == 5
        # each rank holds exactly 1/2 of the padded master buffer
        from apex_tpu.ops import optimizer_kernels as K
        assert state.params_shard.shape[0] * 2 >= K.FLAT_TILE


def test_make_train_step_zero2_amp_overflow_skip():
    """ZeRO path with dynamic loss scaling: an inf gradient on ONE
    shard's microbatch must skip the update on EVERY rank (psum-OR'd
    found_inf) and halve the scale."""
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )

    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])
    params0 = {"w": jnp.ones((2, 1))}

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y) ** 2)

    amp_state = amp.initialize(opt_level="O1", loss_scale="dynamic")
    from apex_tpu.amp import scaler as scaler_lib
    scaler = scaler_lib.init("dynamic", init_scale=2.0 ** 8)
    opt = DistributedFusedAdam(num_shards=2, lr=1e-2, use_pallas=False)
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params0)
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")))
    # poison ONLY the second shard's half of the batch
    X = jnp.ones((8, 2), jnp.float32).at[6, 0].set(jnp.inf)
    Y = jnp.zeros((8, 1), jnp.float32)
    shard0 = jax.device_get(state.params_shard)
    state, scaler, loss = step(state, scaler, (X, Y))
    assert int(jax.device_get(state.step)) == 0  # skipped everywhere
    np.testing.assert_array_equal(jax.device_get(state.params_shard),
                                  shard0)
    assert float(jax.device_get(scaler.scale)) == 2.0 ** 7


def test_make_train_step_zero2_metrics_norms():
    """ZeRO-2 + metrics: param/update norms must be the exact GLOBAL
    values (psum over shards), matching the same run under FusedAdam."""
    from apex_tpu import monitor
    from apex_tpu.optimizers import flat as F
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.optimizers.fused_adam import FusedAdam

    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])
    X = jnp.asarray(np.random.default_rng(11).normal(size=(8, 2)),
                    jnp.float32)
    Y = X @ jnp.array([[1.5], [-0.5]])

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y) ** 2)

    params0 = {"w": jnp.full((2, 1), 0.25)}

    opt = DistributedFusedAdam(num_shards=2, lr=1e-2, use_pallas=False)
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params0)
    step = ddp.make_train_step(loss_fn, opt, mesh, metrics=True,
                               batch_spec=(P("dp"), P("dp")))
    m = monitor.init_metrics()
    state, _, loss, m = step(state, None, (X, Y), m)
    pn = float(jax.device_get(m.param_norm))
    un = float(jax.device_get(m.update_norm))
    assert pn > 0 and un > 0  # were silently 0.0 pre-fix

    ref = FusedAdam(lr=1e-2, use_pallas=False)
    rstate = ref.init(params0)
    g = jax.grad(loss_fn)(F.unflatten(rstate.params, ref.spec), (X, Y))
    _, rnew = ref.step(rstate, g)
    pn_ref = float(jnp.linalg.norm(rstate.params))
    un_ref = float(jnp.linalg.norm(rnew.params - rstate.params))
    np.testing.assert_allclose(pn, pn_ref, rtol=1e-5)
    np.testing.assert_allclose(un, un_ref, rtol=1e-4)
