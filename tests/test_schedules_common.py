"""Tests for pipeline schedules/common helpers, multi_tensor_apply
dispatcher, fp16_utils facade, and the backend probe.

Mirrors the reference's coverage of schedules/common.py (exercised via
tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py) and the L0
multi-tensor tests (tests/L0/run_amp/test_multi_tensor_*.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu
from apex_tpu import fp16_utils
from apex_tpu.multi_tensor_apply import MultiTensorApply, multi_tensor_applier
from apex_tpu.parallel import mesh
from apex_tpu.transformer import _backend_util
from apex_tpu.transformer.pipeline_parallel import common


class TestMultiTensorApply:
    def test_scale(self):
        # ≡ tests/L0/run_amp/test_multi_tensor_scale.py: out = in * scale
        xs = [jnp.arange(12.0).reshape(3, 4), jnp.ones((5,))]

        def scale_op(noop, flats, scale):
            (x,) = flats
            return (x * scale,)

        (out,) = multi_tensor_applier(scale_op, None, [xs], 0.5)
        np.testing.assert_allclose(out[0], np.arange(12.0).reshape(3, 4) * 0.5)
        np.testing.assert_allclose(out[1], 0.5 * np.ones(5))

    def test_axpby_two_lists(self):
        # ≡ test_multi_tensor_axpby.py: out = a*x + b*y
        xs = [jnp.ones((2, 2)), jnp.full((3,), 2.0)]
        ys = [jnp.full((2, 2), 10.0), jnp.full((3,), 20.0)]

        def axpby(noop, flats, a, b):
            x, y = flats
            return (a * x + b * y, None)

        out_x, out_y = multi_tensor_applier(axpby, None, [xs, ys], 2.0, 3.0)
        np.testing.assert_allclose(out_x[0], 32.0 * np.ones((2, 2)))
        np.testing.assert_allclose(out_y[1], 20.0 * np.ones(3))  # unchanged

    def test_mismatched_lists_raise(self):
        with pytest.raises(ValueError):
            MultiTensorApply()(lambda n, f: f, None,
                               [[jnp.ones(3)], [jnp.ones(3), jnp.ones(3)]])


class TestFp16Utils:
    def test_network_to_half_keeps_norm_fp32(self):
        p = {"dense": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones(2)},
             "batchnorm": {"scale": jnp.ones(2)}}
        h = fp16_utils.network_to_half(p, jnp.bfloat16)
        assert h["dense"]["kernel"].dtype == jnp.bfloat16
        assert h["batchnorm"]["scale"].dtype == jnp.float32

    def test_dynamic_loss_scaler(self):
        s = fp16_utils.DynamicLossScaler(init_scale=8.0, scale_window=2)
        s.update_scale(jnp.asarray(True))
        assert s.loss_scale == 4.0
        s.update_scale(jnp.asarray(False))
        s.update_scale(jnp.asarray(False))
        assert s.loss_scale == 8.0

    def test_static_scaler_constant(self):
        s = fp16_utils.LossScaler(64.0)
        s.update_scale(jnp.asarray(True))
        assert s.loss_scale == 64.0
        loss = s.scale_loss(jnp.asarray(2.0))
        assert float(loss) == 128.0

    def test_prep_param_lists_roundtrip(self):
        p = {"w": jnp.ones((2, 2), jnp.bfloat16)}
        model_p, master_p = fp16_utils.prep_param_lists(p)
        assert jax.tree_util.tree_leaves(master_p)[0].dtype == jnp.float32
        back = fp16_utils.master_params_to_model_params(master_p, model_p)
        assert jax.tree_util.tree_leaves(back)[0].dtype == jnp.bfloat16


class TestSchedulesCommon:
    def test_build_model_placement_pp1(self):
        mesh.initialize_model_parallel(tensor_model_parallel_size=1,
                                       pipeline_model_parallel_size=1)
        calls = []

        def provider(pre_process=False, post_process=False):
            calls.append((pre_process, post_process))
            return {"w": jnp.zeros(1)}

        models = common.build_model(provider, wrap_with_ddp=False)
        assert len(models) == 1
        assert calls == [(True, True)]

    def test_build_model_interleaved_placement(self):
        mesh.initialize_model_parallel(tensor_model_parallel_size=1,
                                       pipeline_model_parallel_size=4)
        calls = []

        def provider(pre_process=False, post_process=False):
            calls.append((pre_process, post_process))
            return {}

        models = common.build_model(
            provider, wrap_with_ddp=False,
            virtual_pipeline_model_parallel_size=2)
        assert len(models) == 2
        # Single-controller CPU harness: this process is stage 0 of 4 →
        # chunk 0 is virtual stage 0 (pre), chunk 1 is virtual stage 4
        # of 8 (neither pre nor post).
        assert calls[0] == (True, False)
        assert calls[1] == (False, False)

    def test_build_model_vpp_requires_deep_pipeline(self):
        mesh.initialize_model_parallel(tensor_model_parallel_size=1,
                                       pipeline_model_parallel_size=2)
        with pytest.raises(ValueError):
            common.build_model(lambda **kw: {}, wrap_with_ddp=False,
                               virtual_pipeline_model_parallel_size=2)

    def test_forward_step_divides_loss(self):
        def fwd(batch, model):
            out = batch * model["w"]
            return out, lambda o: jnp.sum(o)

        model = {"w": jnp.asarray(2.0)}
        out, loss = common.forward_step(fwd, jnp.ones(4), model, None,
                                        num_microbatches=4)
        np.testing.assert_allclose(out, 2.0 * np.ones(4))
        assert float(loss) == pytest.approx(8.0 / 4)

    def test_forward_step_uses_input_tensor(self):
        def fwd(x, model):
            return x + 1.0, None

        out, loss = common.forward_step(fwd, jnp.zeros(3), {},
                                        input_tensor=jnp.full((3,), 5.0))
        np.testing.assert_allclose(out, 6.0 * np.ones(3))
        assert loss is None

    def test_backward_step_chain_matches_full_grad(self):
        # Two "stages" f2(f1(x)); chained backward_step must equal
        # jax.grad of the composition (the reference's race-condition
        # style analytic check).
        p1 = {"w": jnp.asarray(3.0)}
        p2 = {"v": jnp.asarray(5.0)}
        x = jnp.arange(4.0)

        def f1(p, x):
            return p["w"] * x

        def f2(p, h):
            return jnp.sum(p["v"] * h ** 2)

        h = f1(p1, x)
        # last stage: seed = 1 (scalar loss)
        g_h, g_p2 = common.backward_step(f2, p2, h)
        g_x, g_p1 = common.backward_step(f1, p1, x, output_grad=g_h)

        full = jax.grad(lambda p1_, p2_: f2(p2_, f1(p1_, x)),
                        argnums=(0, 1))(p1, p2)
        np.testing.assert_allclose(g_p1["w"], full[0]["w"], rtol=1e-6)
        np.testing.assert_allclose(g_p2["v"], full[1]["v"], rtol=1e-6)

    def test_backward_step_grad_scale(self):
        def f(p, x):
            return p["w"] * x

        p = {"w": jnp.asarray(2.0)}
        _, g = common.backward_step(f, p, jnp.ones(3), grad_scale=4.0)
        np.testing.assert_allclose(g["w"], 12.0)

    def test_weight_decay_split(self):
        params = {"block": {"kernel": jnp.ones((3, 3)),
                            "bias": jnp.ones(3)},
                  "layernorm": {"scale": jnp.ones(3)}}
        mask = common.get_params_for_weight_decay_optimization(params)
        assert mask["block"]["kernel"] is True
        assert mask["block"]["bias"] is False
        assert mask["layernorm"]["scale"] is False

    def test_custom_backward_raises(self):
        with pytest.raises(NotImplementedError):
            common.custom_backward(jnp.ones(1), jnp.ones(1))


class TestBackendUtil:
    def test_probe(self):
        assert _backend_util.HAS_UCC is False
        assert _backend_util.default_backend() == "cpu"
        assert _backend_util.backend_available("cpu")
        assert not _backend_util.backend_available("nonexistent")


def test_deprecated_warning_emits():
    with pytest.warns(FutureWarning):
        apex_tpu.deprecated_warning("old thing")
