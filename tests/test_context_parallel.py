"""Ring attention / Ulysses vs dense attention — the long-context CP
layer (beyond reference parity; SURVEY §2.4 CP note)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.flash_attention import attention_reference
from apex_tpu.parallel import mesh as M
from apex_tpu.parallel.context_parallel import (
    ring_attention,
    ulysses_attention,
)

N = 8


def _qkv(b, h, s, d, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (b, h, s, d)),
            jax.random.normal(ks[1], (b, h, s, d)),
            jax.random.normal(ks[2], (b, h, s, d)))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(1, 2, 64, 16)

    f = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "tp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "tp"), P(None, None, "tp"),
                  P(None, None, "tp")),
        out_specs=P(None, None, "tp"), check_vma=False)
    got = f(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_ring_attention_grads():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(1, 1, 32, 8, seed=1)

    def local_grads(q, k, v):
        def loss(q, k, v):
            o = ring_attention(q, k, v, "tp", causal=True)
            return jnp.sum(o ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    spec = P(None, None, "tp")
    g = shard_map(local_grads, mesh=mesh, in_specs=(spec, spec, spec),
                  out_specs=(spec, spec, spec), check_vma=False)(q, k, v)
    r = jax.grad(
        lambda q, k, v: jnp.sum(attention_reference(
            q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, e, n in zip(g, r, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=f"d{n}")


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(2, 8, 64, 16, seed=2)  # h=8 divisible by N

    f = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "tp", causal=causal,
                                          use_flash=False),
        mesh=mesh,
        in_specs=(P(None, None, "tp"),) * 3,
        out_specs=P(None, None, "tp"), check_vma=False)
    got = f(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_segment_ids(causal):
    """Packed-varlen segments with GLOBAL semantics across the ring —
    segments deliberately span shard boundaries (s_local=8, seg len 12)."""
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(2, 2, 64, 16, seed=3)
    seg = (jnp.arange(64) // 12)[None, :].repeat(2, axis=0)

    f = shard_map(
        lambda q, k, v, s: ring_attention(q, k, v, "tp", causal=causal,
                                          segment_ids=s),
        mesh=mesh,
        in_specs=(P(None, None, "tp"),) * 3 + (P(None, "tp"),),
        out_specs=P(None, None, "tp"), check_vma=False)
    got = f(q, k, v, seg)
    want = attention_reference(q, k, v, causal=causal,
                               q_segment_ids=seg, kv_segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_ring_attention_segment_grads():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(1, 1, 64, 16, seed=4)
    seg = (jnp.arange(64) // 24)[None, :]

    def local_grads(q, k, v, s):
        def loss(q, k, v):
            o = ring_attention(q, k, v, "tp", causal=True, segment_ids=s)
            return jnp.sum(o ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    spec = P(None, None, "tp")
    g = shard_map(local_grads, mesh=mesh,
                  in_specs=(spec,) * 3 + (P(None, "tp"),),
                  out_specs=(spec,) * 3, check_vma=False)(q, k, v, seg)
    r = jax.grad(
        lambda q, k, v: jnp.sum(attention_reference(
            q, k, v, causal=True, q_segment_ids=seg,
            kv_segment_ids=seg) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, e, n in zip(g, r, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3, err_msg=f"d{n}")


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_pallas_path(causal):
    """The TPU kernel path (interpret mode on CPU) through the ring:
    per-chunk Pallas flash fwd/bwd inside the scan/switch."""
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(1, 1, 64, 16, seed=5)

    def local_grads(q, k, v):
        def loss(q, k, v):
            o = ring_attention(q, k, v, "tp", causal=causal,
                               use_pallas_override=True)
            return jnp.sum(o ** 2)
        o = ring_attention(q, k, v, "tp", causal=causal,
                           use_pallas_override=True)
        return (o,) + jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    spec = P(None, None, "tp")
    o, gq, gk, gv = shard_map(local_grads, mesh=mesh,
                              in_specs=(spec,) * 3,
                              out_specs=(spec,) * 4,
                              check_vma=False)(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    r = jax.grad(
        lambda q, k, v: jnp.sum(attention_reference(
            q, k, v, causal=causal) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, e, n in zip((gq, gk, gv), r, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3, err_msg=f"d{n}")


def test_ring_attention_causal_skips_chunks():
    """The causal ring must SKIP above-diagonal chunks (a lax.switch /
    HLO conditional whose skip branch does no score work), not mask
    them — check the conditional survives into the lowered HLO."""
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q = jnp.zeros((1, 1, 64, 16))

    f = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "tp", causal=True),
        mesh=mesh, in_specs=(P(None, None, "tp"),) * 3,
        out_specs=P(None, None, "tp"), check_vma=False)
    hlo = jax.jit(f).lower(q, q, q).as_text()
    # StableHLO spells the 3-way branch `stablehlo.case`
    assert "case" in hlo, "causal ring lost its skip branch"


def _ring_grad_temp_bytes(S, d=32):
    """Compiled temp size of a full ring fwd+bwd at global seq S on the
    8-way mesh — the residual-memory probe."""
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q = jnp.zeros((1, 1, S, d), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, "tp", causal=True) ** 2)

    f = shard_map(jax.grad(loss, argnums=(0, 1, 2)), mesh=mesh,
                  in_specs=(P(None, None, "tp"),) * 3,
                  out_specs=(P(None, None, "tp"),) * 3, check_vma=False)
    stats = jax.jit(f).lower(q, q, q).compile().memory_analysis()
    M.destroy_model_parallel()
    return stats.temp_size_in_bytes


def test_ring_attention_memory_linear_in_s_local():
    """custom_vjp residuals are O(s_local · d): doubling the sequence
    doubles compiled temp memory (AD-through-scan would keep
    O(n · s_local²) saved score blocks — ratio ~4 and a huge base)."""
    t16 = _ring_grad_temp_bytes(16384)
    t32 = _ring_grad_temp_bytes(32768)
    ratio = t32 / t16
    assert ratio < 2.6, (t16, t32, ratio)
    # absolute sanity: 32k tokens fwd+bwd in well under n*s_local^2
    # (8 * 4096^2 * 4B = 512 MB); measured ~55 MB
    assert t32 < 200 * 1024 * 1024, t32


def test_ring_attention_128k_causal_fwd_bwd():
    """128k-token causal fwd+bwd on the 8-way mesh (s_local = 16k).

    Parity oracle at this scale: segment ids with length 5120 (NOT a
    divisor of s_local, so segments span shard boundaries) make global
    attention block-diagonal — each segment's output and grads must
    match dense causal attention run on that segment alone.  Verifies a
    shard-interior segment and one spanning the rank0/rank1 boundary."""
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    S, d, SEG = 131072, 32, 5120
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (1, 1, S, d), jnp.float32)
    k = jax.random.normal(ks[1], (1, 1, S, d), jnp.float32)
    v = jax.random.normal(ks[2], (1, 1, S, d), jnp.float32)
    seg = (jnp.arange(S) // SEG)[None, :]

    def local(q, k, v, s):
        def loss(q, k, v):
            o = ring_attention(q, k, v, "tp", causal=True, segment_ids=s)
            return jnp.sum(o ** 2)
        o = ring_attention(q, k, v, "tp", causal=True, segment_ids=s)
        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return o, gq, gk, gv

    spec = P(None, None, "tp")
    o, gq, gk, gv = jax.jit(shard_map(
        local, mesh=mesh, in_specs=(spec,) * 3 + (P(None, "tp"),),
        out_specs=(spec,) * 4, check_vma=False))(q, k, v, seg)

    # segment 3 sits inside rank 0; segment 3*5120=15360..20480 spans
    # the 16384 rank boundary
    for g in (1, 3, 12):
        lo, hi = g * SEG, (g + 1) * SEG
        qs, ks_, vs = q[:, :, lo:hi], k[:, :, lo:hi], v[:, :, lo:hi]

        def seg_loss(qs, ks_, vs):
            return jnp.sum(attention_reference(qs, ks_, vs,
                                               causal=True) ** 2)

        want_o = attention_reference(qs, ks_, vs, causal=True)
        want_g = jax.grad(seg_loss, argnums=(0, 1, 2))(qs, ks_, vs)
        np.testing.assert_allclose(np.asarray(o[:, :, lo:hi]),
                                   np.asarray(want_o), rtol=2e-4,
                                   atol=2e-4, err_msg=f"o seg{g}")
        for a, e, nm in zip((gq, gk, gv), want_g, "qkv"):
            np.testing.assert_allclose(np.asarray(a[:, :, lo:hi]),
                                       np.asarray(e), rtol=2e-3,
                                       atol=2e-3, err_msg=f"d{nm} seg{g}")


# ---------------- zigzag (load-balanced causal) ring --------------------

def _zz_run(q, k, v, seg=None):
    from apex_tpu.parallel.context_parallel import (zigzag_shard,
                                                    zigzag_unshard)
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    qz, kz, vz = (zigzag_shard(x, N) for x in (q, k, v))
    segz = None if seg is None else zigzag_shard(seg, N, axis=1)

    def local(q, k, v, *s):
        s = s[0] if s else None

        def loss(q, k, v):
            o = ring_attention(q, k, v, "tp", causal=True,
                               layout="zigzag", segment_ids=s)
            return jnp.sum(o ** 2)

        o = ring_attention(q, k, v, "tp", causal=True, layout="zigzag",
                           segment_ids=s)
        return (o,) + jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    spec = P(None, None, "tp")
    in_specs = (spec,) * 3 + ((P(None, "tp"),) if seg is not None else ())
    args = (qz, kz, vz) + ((segz,) if seg is not None else ())
    o, gq, gk, gv = jax.jit(shard_map(
        local, mesh=mesh, in_specs=in_specs, out_specs=(spec,) * 4,
        check_vma=False))(*args)
    return tuple(zigzag_unshard(x, N) for x in (o, gq, gk, gv))


def test_zigzag_shard_roundtrip():
    from apex_tpu.parallel.context_parallel import (zigzag_shard,
                                                    zigzag_unshard)
    x = jnp.arange(3 * 32 * 2.0).reshape(3, 1, 32, 2)
    y = zigzag_unshard(zigzag_shard(x, 8), 8)
    np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("with_seg", [False, True])
def test_zigzag_ring_matches_dense(with_seg):
    """Load-balanced causal ring: fwd + grads ≡ dense causal attention
    (and with boundary-spanning segments)."""
    q, k, v = _qkv(1, 2, 128, 16, seed=21)
    seg = (jnp.arange(128) // 24)[None, :] if with_seg else None
    o, gq, gk, gv = _zz_run(q, k, v, seg)
    kw = ({} if seg is None
          else dict(q_segment_ids=seg, kv_segment_ids=seg))
    want = attention_reference(q, k, v, causal=True, **kw)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    r = jax.grad(
        lambda q, k, v: jnp.sum(attention_reference(
            q, k, v, causal=True, **kw) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, e, nm in zip((gq, gk, gv), r, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=f"d{nm}")


def test_zigzag_ring_pallas_path():
    """Zigzag with the Pallas chunk kernels (interpret on CPU)."""
    from apex_tpu.parallel.context_parallel import (zigzag_shard,
                                                    zigzag_unshard)
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(1, 1, 128, 16, seed=22)
    qz, kz, vz = (zigzag_shard(x, N) for x in (q, k, v))

    def local(q, k, v):
        return ring_attention(q, k, v, "tp", causal=True,
                              layout="zigzag", use_pallas_override=True)

    spec = P(None, None, "tp")
    o = jax.jit(shard_map(local, mesh=mesh, in_specs=(spec,) * 3,
                          out_specs=spec, check_vma=False))(qz, kz, vz)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(zigzag_unshard(o, N)),
                               np.asarray(want), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("use_flash", [False, True])
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_segment_ids(causal, use_flash):
    # use_flash=True exercises the all_gather + flash(segment_ids=...)
    # branch; use_pallas_override=True forces the interpret-mode Pallas
    # kernel on CPU (without it flash_attention silently takes the
    # dense fallback off-TPU and the test compares the reference with
    # itself), ADVICE r4
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(2, 8, 64, 16, seed=23)
    seg = (jnp.arange(64) // 20)[None, :].repeat(2, axis=0)

    f = shard_map(
        lambda q, k, v, s: ulysses_attention(q, k, v, "tp", causal=causal,
                                             segment_ids=s,
                                             use_flash=use_flash,
                                             use_pallas_override=use_flash),
        mesh=mesh,
        in_specs=(P(None, None, "tp"),) * 3 + (P(None, "tp"),),
        out_specs=P(None, None, "tp"), check_vma=False)
    got = f(q, k, v, seg)
    want = attention_reference(q, k, v, causal=causal,
                               q_segment_ids=seg, kv_segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


# ----------------------- ring-path attention dropout --------------------


@pytest.mark.parametrize("use_pallas", [True, False])
@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_ring_dropout_matches_single_chip_flash(layout, use_pallas):
    """Ring dropout uses global-coordinate hashing, so with the same
    key the ring output must EQUAL single-chip flash attention over the
    gathered sequence — forward and gradients (VERDICT r4 next-#6).
    use_pallas=False drives the jnp blockwise chunk path, whose
    dropout_keep_dense mask is bit-identical to the kernel hash."""
    from apex_tpu.ops.flash_attention import flash_attention
    from apex_tpu.parallel.context_parallel import (zigzag_shard,
                                                    zigzag_unshard)

    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(1, 2, 128, 16, seed=31)
    key = jax.random.PRNGKey(7)
    rate = 0.3

    def local(q, k, v):
        def loss(q, k, v):
            o = ring_attention(q, k, v, "tp", causal=True, layout=layout,
                               dropout_rate=rate, dropout_key=key,
                               use_pallas_override=use_pallas)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        o = ring_attention(q, k, v, "tp", causal=True, layout=layout,
                           dropout_rate=rate, dropout_key=key,
                           use_pallas_override=use_pallas)
        return (o,) + jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    spec = P(None, None, "tp")
    if layout == "zigzag":
        args = tuple(zigzag_shard(x, N) for x in (q, k, v))
    else:
        args = (q, k, v)
    o, gq, gk, gv = jax.jit(shard_map(
        local, mesh=mesh, in_specs=(spec,) * 3, out_specs=(spec,) * 4,
        check_vma=False))(*args)
    if layout == "zigzag":
        o, gq, gk, gv = (zigzag_unshard(x, N) for x in (o, gq, gk, gv))

    # single-chip oracle with the SAME key: global-coordinate hashing
    # makes the masks identical
    def chip_loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, dropout_rate=rate,
                            dropout_key=key, use_pallas_override=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    o_ref = flash_attention(q, k, v, causal=True, dropout_rate=rate,
                            dropout_key=key, use_pallas_override=True)
    g_ref = jax.grad(chip_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(o_ref, np.float32),
                               rtol=2e-4, atol=2e-4)
    for a, e, nm in zip((gq, gk, gv), g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(e, np.float32),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{nm} {layout}")


@pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 6),
    reason="quarantined on jax<0.6 (this image: 0.4.x): the ring-dropout "
           "path derives its per-chunk key from lax.axis_index, which "
           "this jaxlib's CPU SPMD partitioner lowers to a bare "
           "PartitionId instruction and then rejects with "
           "'UNIMPLEMENTED: PartitionId instruction is not supported "
           "for SPMD partitioning' (jax-ml/jax#14910-class "
           "partition-id-under-jit gap, fixed on newer jaxlibs).  "
           "Pre-dates PR 8 — fails identically at the PR-7 HEAD; "
           "re-enable when the image's jax moves past 0.6.")
def test_ring_dropout_distribution_and_jnp_path():
    """jnp (non-pallas) ring path: dropout drops ~rate of attention
    mass and is deterministic per key; fwd is reproducible."""
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(1, 2, 128, 16, seed=33)
    key = jax.random.PRNGKey(9)

    def run(rate, key):
        f = shard_map(
            lambda q, k, v: ring_attention(
                q, k, v, "tp", causal=False, dropout_rate=rate,
                dropout_key=key, use_pallas_override=False),
            mesh=mesh, in_specs=(P(None, None, "tp"),) * 3,
            out_specs=P(None, None, "tp"), check_vma=False)
        return jax.jit(f)(q, k, v)

    o1 = run(0.4, key)
    o2 = run(0.4, key)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = run(0.4, jax.random.PRNGKey(10))
    assert np.abs(np.asarray(o1, np.float32)
                  - np.asarray(o3, np.float32)).max() > 1e-4
    # no dropout path unchanged by a passed key
    o4 = run(0.0, key)
    o5 = run(0.0, jax.random.PRNGKey(10))
    np.testing.assert_array_equal(np.asarray(o4), np.asarray(o5))


def test_ring_dropout_needs_key():
    q, k, v = _qkv(1, 2, 32, 8, seed=35)
    with pytest.raises(ValueError, match="dropout_key"):
        ring_attention(q, k, v, "tp", dropout_rate=0.1)
