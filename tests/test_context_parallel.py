"""Ring attention / Ulysses vs dense attention — the long-context CP
layer (beyond reference parity; SURVEY §2.4 CP note)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.flash_attention import attention_reference
from apex_tpu.parallel import mesh as M
from apex_tpu.parallel.context_parallel import (
    ring_attention,
    ulysses_attention,
)

N = 8


def _qkv(b, h, s, d, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (b, h, s, d)),
            jax.random.normal(ks[1], (b, h, s, d)),
            jax.random.normal(ks[2], (b, h, s, d)))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(1, 2, 64, 16)

    f = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "tp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "tp"), P(None, None, "tp"),
                  P(None, None, "tp")),
        out_specs=P(None, None, "tp"), check_vma=False)
    got = f(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_ring_attention_grads():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(1, 1, 32, 8, seed=1)

    def local_grads(q, k, v):
        def loss(q, k, v):
            o = ring_attention(q, k, v, "tp", causal=True)
            return jnp.sum(o ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    spec = P(None, None, "tp")
    g = shard_map(local_grads, mesh=mesh, in_specs=(spec, spec, spec),
                  out_specs=(spec, spec, spec), check_vma=False)(q, k, v)
    r = jax.grad(
        lambda q, k, v: jnp.sum(attention_reference(
            q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, e, n in zip(g, r, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=f"d{n}")


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    q, k, v = _qkv(2, 8, 64, 16, seed=2)  # h=8 divisible by N

    f = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "tp", causal=causal,
                                          use_flash=False),
        mesh=mesh,
        in_specs=(P(None, None, "tp"),) * 3,
        out_specs=P(None, None, "tp"), check_vma=False)
    got = f(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
