"""apex_tpu.serve (ISSUE 8): flash-decode kernel parity (bitwise vs
the training flash kernel at q_len=1; interpret-mode Pallas vs the
dense paged oracle across causal x GQA x ragged), the paged KV cache
allocator, and the continuous-batching engine (training-model
fidelity, churn == sequential decoding, zero steady-state recompiles
under admission/retirement, schema-v5 serve stamps)."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import tune
from apex_tpu.models.gpt import GPT, GPTConfig
from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.serve import (
    TRASH_PAGE,
    DecodeEngine,
    KVCacheConfig,
    PagedKVCache,
    ServeConfig,
    flash_decode,
    gather_slot,
    paged_attention_reference,
)

# ------------------------------------------------------------------
# fixtures
# ------------------------------------------------------------------


@pytest.fixture
def tmp_cache(tmp_path, monkeypatch):
    path = tmp_path / "tune.json"
    monkeypatch.setenv(tune.ENV_CACHE_PATH, str(path))
    tune.invalidate()
    yield path
    tune.invalidate()


def _paged_case(rng, ns, hq, hkv, d, page, maxp, lengths, dtype=np.float32):
    """A cache built by writing a KNOWN contiguous (ns, max_kv, hkv, d)
    K/V through a shuffled page table — returns both views so tests
    can compare the kernel against the training kernel on the
    contiguous data."""
    max_kv = maxp * page
    k_dense = rng.randn(ns, max_kv, hkv, d).astype(dtype)
    v_dense = rng.randn(ns, max_kv, hkv, d).astype(dtype)
    n_pages = 1 + ns * maxp
    ids = list(rng.permutation(np.arange(1, n_pages)))
    tbl = np.zeros((ns, maxp), np.int32)
    k_pages = rng.randn(hkv, n_pages, page, d).astype(dtype)  # garbage
    v_pages = rng.randn(hkv, n_pages, page, d).astype(dtype)
    for s in range(ns):
        for t in range(maxp):
            pg = int(ids.pop())
            tbl[s, t] = pg
            k_pages[:, pg] = k_dense[s, t * page:(t + 1) * page].transpose(
                1, 0, 2)
            v_pages[:, pg] = v_dense[s, t * page:(t + 1) * page].transpose(
                1, 0, 2)
    return (jnp.asarray(k_dense), jnp.asarray(v_dense),
            jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(tbl), jnp.asarray(lengths, jnp.int32))


# ------------------------------------------------------------------
# kernel: decode/prefill parity
# ------------------------------------------------------------------


@pytest.mark.parametrize("G", [1, 2])
def test_decode_bitwise_vs_training_flash_qlen1(G):
    """flash_decode at q_len=1 is BITWISE equal to the training flash
    kernel over the same visible keys — ragged lengths spelled as the
    training kernel's kv_segment_ids (the same NEG_INF -> softmax op
    sequence, so equality is exact, not approximate)."""
    rng = np.random.RandomState(0)
    ns, hkv, d, page, maxp = 3, 2, 8, 4, 3
    hq = G * hkv
    max_kv = maxp * page
    lengths = [max_kv, 5, 9]         # full, mid-page, cross-page
    k_dense, v_dense, k_pages, v_pages, tbl, lens = _paged_case(
        rng, ns, hq, hkv, d, page, maxp, lengths)
    q = jnp.asarray(rng.randn(ns, 1, hq, d).astype(np.float32))

    out = flash_decode(q, k_pages, v_pages, tbl, lens)
    assert out.shape == (ns, 1, hq, d)

    for s in range(ns):
        k = jnp.repeat(k_dense[s].transpose(1, 0, 2), G, axis=0)[None]
        v = jnp.repeat(v_dense[s].transpose(1, 0, 2), G, axis=0)[None]
        qs = q[s].transpose(1, 0, 2)[None]          # (1, hq, 1, d)
        if lengths[s] == max_kv:
            ref = flash_attention(qs, k, v)
        else:
            kv_seg = (np.arange(max_kv) < lengths[s]).astype(np.int32)
            ref = flash_attention(
                qs, k, v, q_segment_ids=jnp.ones((1, 1), jnp.int32),
                kv_segment_ids=jnp.asarray(kv_seg[None]))
        np.testing.assert_array_equal(np.asarray(ref[0, :, 0]),
                                      np.asarray(out[s, 0]),
                                      err_msg=f"slot {s}")


@pytest.mark.parametrize("q_len", [1, 2])
@pytest.mark.parametrize("G", [1, 2])
def test_decode_pallas_matches_reference(q_len, G):
    """Interpret-mode Pallas kernel vs the dense paged oracle across
    ragged lengths (inactive / mid-page / page-aligned / full) and
    GQA groups, including the causal-within-new-block q_len > 1 case
    (speculative decoding shape)."""
    rng = np.random.RandomState(1)
    ns, hkv, d, page, maxp = 4, 2, 16, 8, 4
    hq = G * hkv
    lengths = [0, 5, page * 2, maxp * page]
    _, _, k_pages, v_pages, tbl, lens = _paged_case(
        rng, ns, hq, hkv, d, page, maxp, lengths)
    q = jnp.asarray(rng.randn(ns, q_len, hq, d).astype(np.float32))

    ref = paged_attention_reference(q, k_pages, v_pages, tbl, lens)
    pal = flash_decode(q, k_pages, v_pages, tbl, lens,
                       use_pallas_override=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                               atol=2e-5, rtol=1e-5)
    # inactive slot: exact zeros (module contract), not uniform attn
    assert np.all(np.asarray(pal[0]) == 0.0)


def test_decode_head_packing_parity():
    """heads_per_step > 1 computes the same per-head math as unpacked
    (the per-head matmuls are statically unrolled); interpret mode on
    CPU may refuse bit-identity across hp (different stat-tile shapes
    fuse differently), so the gate here is a tight epsilon against
    the SAME dense oracle for every hp.  A non-dividing hp degrades
    to 1 with a one-time warning, never an error (serving must not
    crash on a stale tuned config) — and THAT path is bitwise, it is
    literally the hp=1 kernel."""
    rng = np.random.RandomState(2)
    ns, hkv, d, page, maxp = 2, 4, 8, 8, 2
    _, _, k_pages, v_pages, tbl, lens = _paged_case(
        rng, ns, hkv, hkv, d, page, maxp, [9, 16])
    q = jnp.asarray(rng.randn(ns, 1, hkv, d).astype(np.float32))

    ref = paged_attention_reference(q, k_pages, v_pages, tbl, lens)
    base = flash_decode(q, k_pages, v_pages, tbl, lens,
                        use_pallas_override=True, heads_per_step=1)
    for hp in (2, 4):
        packed = flash_decode(q, k_pages, v_pages, tbl, lens,
                              use_pallas_override=True,
                              heads_per_step=hp)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(packed),
                                   atol=1e-6, rtol=1e-6)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        bad = flash_decode(q, k_pages, v_pages, tbl, lens,
                           use_pallas_override=True, heads_per_step=3)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(bad))
    assert any("does not divide" in str(r.message) for r in rec)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        zero = flash_decode(q, k_pages, v_pages, tbl, lens,
                            use_pallas_override=True, heads_per_step=0)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(zero))
    assert any("is not positive" in str(r.message) for r in rec)


def test_decode_tuner_lookup(tmp_cache):
    """A tuned flash_decode entry drives heads_per_step through the
    cache (decode_attrs is the shared key schema); an out-of-range
    cached hp is ignored with a warning — byte-identical output
    either way."""
    rng = np.random.RandomState(3)
    ns, hkv, d, page, maxp = 2, 2, 8, 4, 2
    _, _, k_pages, v_pages, tbl, lens = _paged_case(
        rng, ns, hkv, hkv, d, page, maxp, [3, 8])
    q = jnp.asarray(rng.randn(ns, 1, hkv, d).astype(np.float32))
    base = flash_decode(q, k_pages, v_pages, tbl, lens,
                        use_pallas_override=True)

    attrs = tune.decode_attrs(ns, 1, hkv, hkv, d, page, q.dtype)
    tune.record("flash_decode", attrs, {"heads_per_step": 2})
    tune.invalidate()
    tuned = flash_decode(q, k_pages, v_pages, tbl, lens,
                         use_pallas_override=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(tuned))

    tune.record("flash_decode", attrs, {"heads_per_step": 999})
    tune.invalidate()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        junk = flash_decode(q, k_pages, v_pages, tbl, lens,
                            use_pallas_override=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(junk))
    assert any("out-of-range" in str(r.message) for r in rec)


# ------------------------------------------------------------------
# paged KV cache allocator
# ------------------------------------------------------------------


def _kv_cfg(**kw):
    base = dict(n_layers=2, n_kv_heads=2, head_dim=8, n_slots=4,
                n_pages=9, pages_per_slot_max=3, page_size=4,
                dtype=jnp.float32)
    base.update(kw)
    return KVCacheConfig(**base)


def test_allocator_accounting():
    cache = PagedKVCache(_kv_cfg())
    assert cache.free_pages == 8               # page 0 reserved
    row = cache.allocate_slot(0, 9)            # 3 pages
    assert row is not None and cache.free_pages == 5
    assert TRASH_PAGE not in cache.slot_pages(0)
    # double allocation of a live slot is a bug, loudly
    with pytest.raises(ValueError, match="already holds"):
        cache.allocate_slot(0, 1)
    # exhaustion -> None (admission control), nothing leaked
    assert cache.allocate_slot(1, 12) is not None   # 3 more
    assert cache.allocate_slot(2, 12) is None       # only 2 left
    assert cache.free_pages == 2
    cache.release_slot(0)
    assert cache.free_pages == 5
    assert cache.allocate_slot(2, 12) is not None
    # over-table-row requests are rejected even with free pages
    cache.release_slot(1)
    cache.release_slot(2)
    assert cache.allocate_slot(3, 13) is None      # needs 4 > max 3
    assert cache.free_pages == 8


def test_cache_config_pricing_and_tuner(tmp_cache):
    cfg = _kv_cfg()
    assert cfg.pages_for(0) == 0
    assert cfg.pages_for(1) == 1 and cfg.pages_for(5) == 2
    assert cfg.max_seq_len == 12
    itemsize = 4
    per_tok = 2 * 2 * 2 * 8 * itemsize         # layers*kv*d*(K+V)
    assert cfg.bytes_per_token() == per_tok
    assert cfg.page_bytes() == per_tok * cfg.page_size
    assert cfg.pool_bytes() == cfg.n_pages * cfg.page_bytes()
    # partial last page is paid in full — the per-user price
    assert cfg.bytes_per_user(5) == 2 * cfg.page_bytes()

    # page_size None -> tuner-owned (serve_page), heuristic fallback
    auto = _kv_cfg(page_size=None)
    assert auto.page_size == 128               # lane-width heuristic
    tune.record("serve_page", tune.serve_page_attrs(2, 8, jnp.float32),
                {"page_size": 16})
    tune.invalidate()
    tuned = _kv_cfg(page_size=None)
    assert tuned.page_size == 16
    tune.record("serve_page", tune.serve_page_attrs(2, 8, jnp.float32),
                {"page_size": 7})              # unaligned nonsense
    tune.invalidate()
    assert _kv_cfg(page_size=None).page_size == 128


def test_gather_slot_roundtrip():
    rng = np.random.RandomState(4)
    cfg = _kv_cfg()
    cache = PagedKVCache(cfg)
    row = cache.allocate_slot(1, 9)
    k_pages = rng.randn(cfg.n_layers, cfg.n_kv_heads, cfg.n_pages,
                        cfg.page_size, cfg.head_dim).astype(np.float32)
    k, _ = gather_slot(k_pages, k_pages, row, 9)
    assert k.shape == (9, cfg.n_kv_heads, cfg.head_dim)
    np.testing.assert_array_equal(
        k[:4], k_pages[0][:, row[0]].transpose(1, 0, 2))


# ------------------------------------------------------------------
# engine
# ------------------------------------------------------------------

_CFG = GPTConfig(vocab_size=64, seq_len=64, hidden=32, num_layers=2,
                 num_heads=4, dropout=0.0)
_SC = ServeConfig(n_slots=3, max_prompt_len=8, max_new_cap=8,
                  page_size=4)


def _params(seed=7, spread=20.0):
    """GPT weights with the POSITION embedding scaled up so greedy
    decoding produces VARIED tokens (a raw random init argmaxes to one
    id forever, which would let a broken scheduler pass the churn
    test trivially)."""
    params = GPT(_CFG).init(jax.random.PRNGKey(seed))
    params["pos_embed"] = params["pos_embed"] * spread
    return params


def test_engine_matches_training_model():
    """Teacher-forced fidelity: feed prompt + engine-generated tokens
    through the TRAINING GPT forward (shard_map, tp=1) — at every
    position the training model's greedy next token must be exactly
    the token the serving engine produced (prefill and paged decode
    both faithful to the trained function)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel import mesh as M

    params = _params()
    eng = DecodeEngine(_CFG, params, _SC)
    prompt = [5, 9, 2, 17, 33]
    eng.submit(prompt, max_new_tokens=6)
    toks = eng.run()[0].tokens
    assert len(toks) == 6
    assert len(set(toks)) > 1, "degenerate decode — test has no teeth"

    model = GPT(_CFG)
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=1)

    def fwd(p, tokens):
        return model.logits_local(p, model.apply(p, tokens))

    f = shard_map(fwd, mesh=mesh, in_specs=(model.partition_specs(), P()),
                  out_specs=P(), check_vma=False)
    seq = prompt + toks
    logits = f(params, jnp.asarray([seq], jnp.int32))  # (S, 1, V)
    for i in range(len(prompt) - 1, len(seq) - 1):
        assert int(jnp.argmax(logits[i, 0])) == seq[i + 1], i
    M.destroy_model_parallel()


def test_engine_churn_matches_sequential():
    """The continuous-batching acceptance gate: interleaved
    admissions/retirements with MORE requests than slots produce (a)
    bitwise the same per-stream outputs as decoding each stream alone
    and (b) ZERO steady-state recompiles (sentry-enforced) and (c) a
    drained pool afterwards."""
    params = _params(seed=11)
    prompts = [[1, 2], [3, 4, 5], [7], [9, 10, 11, 12], [13, 14],
               [15, 16, 17, 18, 19], [21], [22, 23]]
    budgets = [4, 6, 3, 5, 8, 2, 7, 4]         # ragged retirement times

    # solo baseline: ONE engine decoding one stream at a time (slots
    # reset on retirement, so serial submits are isolated runs — and
    # reusing the compiled step keeps the test fast)
    solo = DecodeEngine(_CFG, params, _SC)
    sequential = {}
    for i, (p, b) in enumerate(zip(prompts, budgets)):
        solo.submit(p, b)
        sequential[i] = solo.run()[0].tokens
    assert solo.recompile_ok, solo.sentry.summary()

    eng = DecodeEngine(_CFG, params, _SC)      # 3 slots, 8 streams
    rids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    finished = {f.request_id: f.tokens for f in eng.run()}
    assert len(finished) == len(prompts)
    for i, rid in enumerate(rids):
        assert finished[rid] == sequential[i], (
            f"stream {i}: churn {finished[rid]} != solo {sequential[i]}")
    assert eng.recompile_ok, eng.sentry.summary()
    assert eng.sentry.steady_recompiles == 0
    assert eng.cache.free_pages == eng.kv_config.usable_pages
    assert eng.stats()["live"] == 0


def test_engine_eos_and_validation():
    params = _params(seed=11)
    # find the first token the model emits for this prompt, then make
    # it the EOS: generation must stop at length 1
    probe = DecodeEngine(_CFG, params, _SC)
    probe.submit([1, 2, 3], 4)
    first = probe.run()[0].tokens[0]
    eos_eng = DecodeEngine(
        _CFG, params,
        ServeConfig(n_slots=3, max_prompt_len=8, max_new_cap=8,
                    page_size=4, eos_id=int(first)))
    eos_eng.submit([1, 2, 3], 8)
    out = eos_eng.run()[0]
    assert out.tokens == [first]

    with pytest.raises(ValueError, match="max_prompt_len"):
        probe.submit(list(range(9)), 2)
    with pytest.raises(ValueError, match="max_new_tokens"):
        probe.submit([1], 99)
    with pytest.raises(ValueError, match="empty"):
        probe.submit([], 2)
    with pytest.raises(ValueError, match="seq_len"):
        DecodeEngine(_CFG, params,
                     ServeConfig(n_slots=1, max_prompt_len=64,
                                 max_new_cap=64, page_size=4))

    # a request NO future state can admit (explicit n_pages undercuts
    # the per-slot worst case) is rejected at submit, not queued to
    # spin the engine forever behind the head of the line
    tiny_pool = DecodeEngine(
        _CFG, params, ServeConfig(n_slots=2, max_prompt_len=8,
                                  max_new_cap=8, page_size=4, n_pages=3))
    with pytest.raises(ValueError, match="at most 2 per request"):
        tiny_pool.submit(list(range(1, 9)), 8)     # needs 4 > 2 usable
    tiny_pool.submit([1, 2, 3], 4)                 # 2 pages: fits
    assert len(tiny_pool.run()[0].tokens) == 4


def test_steady_mark_has_bounded_warmup():
    """The recompile gate must FAIL CLOSED: a decode step that
    retraces on every call never produces a compile-free call, so
    without the warmup cap it would stay 'warming up' forever and
    stamp recompile_ok=True vacuously.  Shim the sentry to claim every
    call compiled and assert the engine still marks steady."""
    from apex_tpu.serve.engine import _STEADY_WARMUP_CAP

    params = _params(seed=11)
    eng = DecodeEngine(_CFG, params, _SC)
    real = eng.sentry

    class AlwaysCompilingShim:
        marked_at = None
        steady_recompiles = 0

        @property
        def calls(self):
            return real.calls

        @property
        def events(self):
            return [{"call": real.calls}]     # "this call compiled"

        def mark_steady(self):
            self.marked_at = real.calls

        def __call__(self, *args):
            return real(*args)

    eng.sentry = AlwaysCompilingShim()
    eng.submit([1, 2, 3], 8)                  # 8 decode steps > cap
    while eng.pending:
        eng.step()
    assert eng.sentry.marked_at == _STEADY_WARMUP_CAP, \
        eng.sentry.marked_at


def test_engine_emit_logits():
    """emit_logits=True threads the (n_slots, V) fp32 decode logits
    out of the step; their greedy argmax IS the token the engine
    appends (the hook a sampling extension builds on)."""
    params = _params(seed=11)
    eng = DecodeEngine(
        _CFG, params,
        ServeConfig(n_slots=2, max_prompt_len=8, max_new_cap=8,
                    page_size=4, emit_logits=True))
    eng.submit([5, 9, 2], 4)
    seen, fins = [], []
    while eng.pending:
        eng.step()
        if eng.last_logits is not None:
            assert eng.last_logits.shape == (2, _CFG.vocab_size)
            assert eng.last_logits.dtype == jnp.float32
            seen.append(int(jnp.argmax(eng.last_logits[0])))
        fins.extend(eng.poll())
    toks = fins[0].tokens
    assert len(toks) == 4
    # prefill emits token 0; decode steps 1..3 emit the rest, each the
    # argmax of that step's logits (the last seen entry is the stale
    # final-retire read and is ignored)
    assert toks[1:] == seen[:3]
    assert eng.recompile_ok


def test_measure_decode_accounting():
    """The shared drive-and-measure helper (bench + example both quote
    it): every request retired, tokens counted are the tokens emitted,
    churn steps counted, device-synced timings positive, and the
    drain guard raises instead of spinning."""
    from apex_tpu.serve import measure_decode

    params = _params(seed=11)
    eng = DecodeEngine(_CFG, params, _SC)      # 3 slots
    budgets = [3, 5, 2, 4, 6]
    for i, b in enumerate(budgets):            # 5 streams > 3 slots
        eng.submit([i + 1, i + 2], b)
    m = measure_decode(eng)
    assert len(m["finished"]) == len(budgets)
    assert (sorted(len(f.tokens) for f in m["finished"])
            == sorted(budgets))
    assert m["steps"] == len(m["per_step_s"])
    assert 0 < m["churn_steps"] < m["steps"]
    assert m["pure_decode_steps"] > 0
    assert m["tokens_per_sec"] > 0
    assert 0 < m["p50_ms"] <= m["p99_ms"]
    assert m["recompile_ok"] is True
    assert all(t > 0 for t in m["per_step_s"])
    # a drained engine's step() skips the all-inactive decode forward
    calls = eng.sentry.calls
    assert eng.step() == (0, 0)
    assert eng.sentry.calls == calls

    eng2 = DecodeEngine(_CFG, params, _SC)
    with pytest.raises(ValueError, match="no pending"):
        measure_decode(eng2)
    eng2.submit([1, 2], 8)
    with pytest.raises(RuntimeError, match="still live"):
        measure_decode(eng2, max_steps=2)


def test_engine_serve_stamps_validate_v5():
    """bench.py's serve_* stamps are SCHEMA v5 — a full record carrying
    them validates; nulls and non-scalars under the reserved serve_
    prefix are rejected."""
    from apex_tpu import monitor
    from bench import _stamp_serve

    base = {
        "monitor_schema_version": monitor.SCHEMA_VERSION, "step": 1,
        "loss": 1.0, "grad_norm": 1.0, "param_norm": 1.0,
        "update_norm": 0.1, "loss_scale": 1.0, "overflow_count": 0,
        "skipped_steps": 0, "tokens_seen": 10.0, "step_time_ms": 1.0,
        "tokens_per_sec": 10.0, "mfu": 0.1,
    }
    sweep = {"1": {"tokens_per_sec": 10.0, "p50_ms": 1.0, "p99_ms": 2.0,
                   "steps": 4, "recompile_ok": True},
             "64": {"tokens_per_sec": 99.5, "p50_ms": 3.0, "p99_ms": 4.5,
                    "steps": 9, "recompile_ok": True}}
    rec = dict(base)
    _stamp_serve(rec, sweep)
    assert rec["serve_streams"] == 64
    assert rec["serve_decode_tokens_per_sec"] == 99.5
    assert rec["serve_recompile_ok"] is True
    monitor.validate_record(rec)

    with pytest.raises(ValueError, match="serve_streams"):
        monitor.validate_record(dict(rec, serve_streams=None))
    with pytest.raises(ValueError, match="serve_recompile_ok"):
        monitor.validate_record(dict(rec, serve_recompile_ok=1))
    with pytest.raises(ValueError, match="scalar"):
        monitor.validate_record(dict(rec, serve_extra=[1, 2]))
    # one churned concurrency poisons the verdict
    bad = dict(base)
    _stamp_serve(bad, {"1": dict(sweep["1"], recompile_ok=False)})
    assert bad["serve_recompile_ok"] is False
