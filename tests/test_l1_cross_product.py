"""L1 tier — cross-product integration matrix.

≡ tests/L1/cross_product in the reference (tests/L1/common/run_test.sh:
17-60): full ResNet training runs over {O0..O3} × {loss_scale} ×
{keep_batchnorm_fp32} × {fused optimizer}, with loss-trajectory parity
between configurations checked the way tests/L1/common/compare.py does
against stored baselines.  Runs on the 8-device CPU mesh; each config is
trained once and trajectories are compared pairwise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# the cross-product matrix is the heavy tier by definition (pytest.ini:
# "l1: heavy tier (large-scale / cross-product tests)"); the default
# tier's AMP coverage lives in test_amp_casts.py + the e2e model tests
pytestmark = pytest.mark.l1
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.resnet import ResNet
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M

STEPS = 8

# name -> (opt_level, policy overrides, optimizer)
CONFIGS = {
    "O0": ("O0", {}, "sgd"),
    "O1": ("O1", {}, "sgd"),                      # dynamic scale 2**16
    "O1_static128": ("O1", {"loss_scale": 128.0}, "sgd"),
    "O1_noscale": ("O1", {"loss_scale": 1.0}, "sgd"),
    "O2": ("O2", {}, "sgd"),                      # bf16 params + masters
    "O2_nokeepbn": ("O2", {"keep_norm_fp32": False}, "sgd"),
    "O3": ("O3", {}, "sgd"),                      # pure bf16, speed mode
    "O1_adam": ("O1", {}, "adam"),
}

_cache = {}


def _train(name):
    if name in _cache:
        return _cache[name]
    opt_level, overrides, opt_name = CONFIGS[name]
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel()  # dp=8
    model = ResNet("resnet10", num_classes=10, axis_name="dp",
                   small_input=True)
    params, mstate = model.init(jax.random.PRNGKey(42))
    amp_state = amp.initialize(opt_level=opt_level, **overrides)
    if amp_state.policy.param_dtype != jnp.float32:
        if amp_state.policy.keep_norm_fp32:
            params = amp.convert_network(params, amp_state.policy.param_dtype)
        else:
            params = amp_state.policy.cast_to_param(params)

    def loss_fn(p, ms, batch):
        x, y = batch
        logits, new_ms = model.apply(p, ms, x, training=True)
        loss = jnp.mean(softmax_cross_entropy_loss(
            logits.astype(jnp.float32), y))
        return loss, new_ms

    if opt_name == "adam":
        opt = FusedAdam(lr=1e-2, use_pallas=False)
    else:
        opt = FusedSGD(lr=0.1, momentum=0.9, use_pallas=False)
    state = opt.init(params)
    scaler = amp_state.loss_scalers[0]
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               with_state=True, donate=False)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 10)
    losses = []
    for _ in range(STEPS):
        state, scaler, mstate, loss = step(state, scaler, mstate, (x, y))
        losses.append(float(loss))
    M.destroy_model_parallel()
    _cache[name] = losses
    return losses


@pytest.mark.parametrize("name", list(CONFIGS))
def test_config_trains(name):
    """Every cross-product cell runs to finite, decreasing loss
    (≡ run_test.sh "intended" runs)."""
    losses = _train(name)
    assert all(np.isfinite(losses)), (name, losses)
    # bf16-param modes (O2/O3) round the weights each step, so their
    # short-horizon trajectory is noisier — require progress, not
    # monotonicity (the reference compares 500-iteration dumps)
    if CONFIGS[name][0] in ("O2", "O3"):
        assert min(losses[1:]) < losses[0], (name, losses)
    else:
        assert losses[-1] < losses[0] * 0.95, (name, losses)


@pytest.mark.parametrize("other,rtol", [
    ("O1", 5e-2), ("O1_static128", 5e-2), ("O1_noscale", 5e-2),
    ("O1_adam", None),  # different optimizer: trains, no parity claim
    ("O2", 1.5e-1), ("O2_nokeepbn", 2e-1), ("O3", None),
])
def test_parity_vs_O0(other, rtol):
    """Loss-trajectory parity across opt-levels ≡ compare.py:30-60.

    Scaling by powers of two and bf16 compute keep O1-family runs on the
    O0 trajectory; O2/O3 (bf16 params) drift further but must track.
    O3 and the Adam variant only assert finite training (the reference
    treats O3 as the "speed of light" mode with no accuracy contract).
    """
    base = _train("O0")
    other_losses = _train(other)
    if rtol is not None:
        np.testing.assert_allclose(base, other_losses, rtol=rtol,
                                   atol=5e-2)
