"""Native host-runtime tests (C++ ctypes lib + fallbacks).
≡ the reference's apex_C flatten/unflatten and multi_tensor_apply
metadata behavior."""

import numpy as np

from apex_tpu import csrc


def test_native_lib_builds():
    assert csrc.available(), "host runtime .so failed to build"


def test_flat_layout():
    offsets, total = csrc.flat_layout([100, 50, 128], align=128)
    np.testing.assert_array_equal(offsets, [0, 128, 256])
    assert total == 384
    offsets2, total2 = csrc.flat_layout([100, 50, 128], align=1)
    np.testing.assert_array_equal(offsets2, [0, 100, 150])
    assert total2 == 278


def test_chunk_plan():
    plan = csrc.chunk_plan([5, 12], chunk_size=5)
    expect = [(0, 0, 5), (1, 0, 5), (1, 5, 5), (1, 10, 2)]
    np.testing.assert_array_equal(plan, expect)


def test_shuffle_deterministic_permutation():
    a = csrc.shuffle_indices(1000, seed=42)
    b = csrc.shuffle_indices(1000, seed=42)
    c = csrc.shuffle_indices(1000, seed=43)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert sorted(a.tolist()) == list(range(1000))


def test_gather_rows():
    ds = np.arange(40, dtype=np.float32).reshape(10, 4)
    idx = [3, 0, 7, 7]
    out = csrc.gather_rows(ds, idx)
    np.testing.assert_array_equal(out, ds[idx])

    ds_i = np.arange(30, dtype=np.int32).reshape(10, 3)
    out_i = csrc.gather_rows(ds_i, [9, 1])
    np.testing.assert_array_equal(out_i, ds_i[[9, 1]])
