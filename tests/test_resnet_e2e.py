"""End-to-end slice ≡ tests/L1 cross_product: ResNet (CIFAR stand-in)
training with AMP opt-levels + DP mesh + SyncBN + fused optimizer —
loss decreases, and O0 vs O1 trajectories agree (parity across
opt-levels, ≡ tests/L1/common/compare.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.resnet import ResNet
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M


def _data(n=16, classes=10):
    x = jax.random.normal(jax.random.PRNGKey(0), (n, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, classes)
    return x, y


def _train(opt_level, steps=8):
    mesh = M.initialize_model_parallel()  # dp=8
    model = ResNet("resnet10", num_classes=10, axis_name="dp",
                   small_input=True)
    params, mstate = model.init(jax.random.PRNGKey(42))
    amp_state = amp.initialize(opt_level=opt_level)
    if amp_state.policy.param_dtype != jnp.float32:
        params = amp.convert_network(params, amp_state.policy.param_dtype)

    def loss_fn(p, ms, batch):
        x, y = batch
        logits, new_ms = model.apply(p, ms, x, training=True)
        loss = jnp.mean(softmax_cross_entropy_loss(
            logits.astype(jnp.float32), y))
        return loss, new_ms

    opt = FusedSGD(lr=0.1, momentum=0.9, use_pallas=False)
    state = opt.init(params)
    scaler = amp_state.loss_scalers[0]
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               with_state=True, donate=False)
    x, y = _data()
    losses = []
    for _ in range(steps):
        state, scaler, mstate, loss = step(state, scaler, mstate, (x, y))
        losses.append(float(loss))
    M.destroy_model_parallel()
    return losses


@pytest.mark.parametrize("opt_level", ["O0", "O1"])
def test_resnet_trains(opt_level):
    losses = _train(opt_level)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.8


def test_opt_level_parity():
    """O0 vs O1 loss trajectories stay within bf16 tolerance
    (≡ tests/L1/common/compare.py:30-60 parity check)."""
    l0 = _train("O0", steps=3)
    l1 = _train("O1", steps=3)
    np.testing.assert_allclose(l0, l1, rtol=5e-2, atol=5e-2)


def test_space_to_depth_stem_exact():
    """stem="space_to_depth" computes the SAME function as the 7x7/s2
    stem (identical params), to fp32 numerics."""
    import numpy as np

    from apex_tpu.models.resnet import ResNet

    m1 = ResNet("resnet10", num_classes=10)
    m2 = ResNet("resnet10", num_classes=10, stem="space_to_depth")
    params, state = m1.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))
    y1, _ = m1.apply(params, state, x, training=False)
    y2, _ = m2.apply(params, state, x, training=False)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda p: m1.apply(p, state, x, training=False)[0].sum()
                  )(params)
    g2 = jax.grad(lambda p: m2.apply(p, state, x, training=False)[0].sum()
                  )(params)
    np.testing.assert_allclose(
        np.asarray(g1["conv_stem"]), np.asarray(g2["conv_stem"]),
        rtol=1e-4, atol=1e-4)


def test_max_pool2d_routed_backward_matches_select_and_scatter():
    """Routed maxpool backward ≡ XLA SelectAndScatter gradient, incl.
    first-wins tie routing (tie-heavy int-valued inputs)."""
    import numpy as np
    from jax import lax

    from apex_tpu.ops.pooling import max_pool2d

    def ref(x):
        return lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                                 (1, 2, 2, 1), "SAME")

    for seed, tie_heavy in ((0, False), (1, True)):
        k = jax.random.PRNGKey(seed)
        if tie_heavy:
            # small-int grid + relu-style zeros → frequent exact ties
            x = jax.random.randint(k, (2, 16, 16, 8), 0, 3).astype(
                jnp.float32)
        else:
            x = jax.random.normal(k, (2, 16, 16, 8))
        dy = jax.random.normal(jax.random.PRNGKey(seed + 9),
                               ref(x).shape)
        y1, vjp1 = jax.vjp(ref, x)
        y2, vjp2 = jax.vjp(lambda x: max_pool2d(
            x, routed_backward=True), x)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
        np.testing.assert_allclose(np.asarray(vjp1(dy)[0]),
                                   np.asarray(vjp2(dy)[0]),
                                   rtol=1e-6, atol=1e-6,
                                   err_msg=f"tie_heavy={tie_heavy}")


def test_max_pool2d_odd_sizes_and_valid():
    import numpy as np
    from jax import lax

    from apex_tpu.ops.pooling import max_pool2d

    x = jax.random.normal(jax.random.PRNGKey(3), (1, 13, 17, 4))
    # only stride-(2,2) configs exercise the routed backward;
    # max_pool2d falls back to reduce_window+XLA AD otherwise (so a
    # stride-(1,1) case here would compare the fallback with itself)
    for padding in ("SAME", "VALID"):
        for window, strides in (((3, 3), (2, 2)), ((2, 2), (2, 2))):
            def ref(x):
                return lax.reduce_window(
                    x, -jnp.inf, lax.max,
                    (1,) + window + (1,), (1,) + strides + (1,), padding)

            dy = jax.random.normal(jax.random.PRNGKey(4), ref(x).shape)
            y1, vjp1 = jax.vjp(ref, x)
            y2, vjp2 = jax.vjp(
                lambda x: max_pool2d(x, window, strides, padding,
                     routed_backward=True), x)
            np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
            np.testing.assert_allclose(
                np.asarray(vjp1(dy)[0]), np.asarray(vjp2(dy)[0]),
                rtol=1e-6, atol=1e-6,
                err_msg=f"{padding} {window} {strides}")
