"""End-to-end slice ≡ tests/L1 cross_product: ResNet (CIFAR stand-in)
training with AMP opt-levels + DP mesh + SyncBN + fused optimizer —
loss decreases, and O0 vs O1 trajectories agree (parity across
opt-levels, ≡ tests/L1/common/compare.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.resnet import ResNet
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M


def _data(n=16, classes=10):
    x = jax.random.normal(jax.random.PRNGKey(0), (n, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, classes)
    return x, y


def _train(opt_level, steps=8):
    mesh = M.initialize_model_parallel()  # dp=8
    model = ResNet("resnet10", num_classes=10, axis_name="dp",
                   small_input=True)
    params, mstate = model.init(jax.random.PRNGKey(42))
    amp_state = amp.initialize(opt_level=opt_level)
    if amp_state.policy.param_dtype != jnp.float32:
        params = amp.convert_network(params, amp_state.policy.param_dtype)

    def loss_fn(p, ms, batch):
        x, y = batch
        logits, new_ms = model.apply(p, ms, x, training=True)
        loss = jnp.mean(softmax_cross_entropy_loss(
            logits.astype(jnp.float32), y))
        return loss, new_ms

    opt = FusedSGD(lr=0.1, momentum=0.9, use_pallas=False)
    state = opt.init(params)
    scaler = amp_state.loss_scalers[0]
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               with_state=True, donate=False)
    x, y = _data()
    losses = []
    for _ in range(steps):
        state, scaler, mstate, loss = step(state, scaler, mstate, (x, y))
        losses.append(float(loss))
    M.destroy_model_parallel()
    return losses


@pytest.mark.parametrize("opt_level", ["O0", "O1"])
def test_resnet_trains(opt_level):
    losses = _train(opt_level)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.8


def test_opt_level_parity():
    """O0 vs O1 loss trajectories stay within bf16 tolerance
    (≡ tests/L1/common/compare.py:30-60 parity check)."""
    l0 = _train("O0", steps=3)
    l1 = _train("O1", steps=3)
    np.testing.assert_allclose(l0, l1, rtol=5e-2, atol=5e-2)
