"""Contrib op tests ≡ apex/contrib/test/*: multihead attention vs
reference math, focal loss vs formula, index_mul_2d fwd/bwd, RNN-T
transducer loss vs numpy DP oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
from apex_tpu.contrib.transducer import TransducerJoint, transducer_loss


# ---------------------------- multihead attn --------------------------------

def _ref_self_attn(params, x, nh, norm_add=False):
    """Pure reference math (≡ the python fallback in multihead_attn)."""
    from apex_tpu.ops.layer_norm import layer_norm_reference
    residual = x
    if norm_add:
        x = layer_norm_reference(x, params["ln"]["weight"],
                                 params["ln"]["bias"])
    s, b, e = x.shape
    hd = e // nh
    qkv = x @ params["qkv_weight"]
    qkv = qkv.reshape(s, b, 3, nh, hd)
    q, k, v = (qkv[:, :, i].transpose(1, 2, 0, 3) for i in range(3))
    sc = jnp.einsum("bnqd,bnkd->bnqk", q, k) * (hd ** -0.5)
    p = jax.nn.softmax(sc, axis=-1)
    ctx = jnp.einsum("bnqk,bnkd->bnqd", p, v)
    out = ctx.transpose(2, 0, 1, 3).reshape(s, b, e) @ params["out_weight"]
    if norm_add:
        out = out + residual
    return out


@pytest.mark.parametrize("norm_add", [False, True])
def test_self_multihead_attn(norm_add):
    mha = SelfMultiheadAttn(32, 4, bias=False, include_norm_add=norm_add)
    p = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 2, 32))
    got = mha.apply(p, x, use_pallas_override=True)
    want = _ref_self_attn(p, x, 4, norm_add)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_encdec_multihead_attn():
    mha = EncdecMultiheadAttn(32, 4, bias=True)
    p = mha.init(jax.random.PRNGKey(2))
    q = jax.random.normal(jax.random.PRNGKey(3), (8, 2, 32))
    enc = jax.random.normal(jax.random.PRNGKey(4), (16, 2, 32))
    out = mha.apply(p, q, key=enc, use_pallas_override=True)
    assert out.shape == (8, 2, 32)
    # grads flow to all params
    g = jax.grad(lambda pp: jnp.sum(mha.apply(
        pp, q, key=enc, use_pallas_override=True) ** 2))(p)
    assert all(np.abs(np.asarray(l)).max() > 0
               for l in jax.tree_util.tree_leaves(g))


def test_self_attn_with_mask():
    mha = SelfMultiheadAttn(16, 2)
    p = mha.init(jax.random.PRNGKey(5))
    x = jax.random.normal(jax.random.PRNGKey(6), (8, 2, 16))
    mask = jnp.zeros((2, 1, 8, 8), bool).at[:, :, :, 6:].set(True)
    out = mha.apply(p, x, mask=mask)
    # masked keys don't affect output: perturb x at masked positions
    x2 = x.at[6:].set(0.0)
    out2 = mha.apply(p, x2, mask=mask)
    np.testing.assert_allclose(np.asarray(out[:6]), np.asarray(out2[:6]),
                               rtol=1e-4, atol=1e-5)


# ------------------------------ focal loss ----------------------------------

def test_focal_loss_matches_formula():
    x = jax.random.normal(jax.random.PRNGKey(7), (10, 8))
    t = jnp.array([0, 1, 2, -1, -1, 3, -2, 7, 0, -1])
    nps = jnp.float32(4.0)
    got = float(focal_loss(x, t, nps, 8))

    xx = np.asarray(x, np.float64)
    want = 0.0
    for i in range(10):
        if int(t[i]) == -2:
            continue
        y = np.zeros(8)
        if int(t[i]) >= 0:
            y[int(t[i])] = 1.0
        p = 1 / (1 + np.exp(-xx[i]))
        ce = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        pt = p * y + (1 - p) * (1 - y)
        at = 0.25 * y + 0.75 * (1 - y)
        want += np.sum(at * (1 - pt) ** 2 * ce)
    np.testing.assert_allclose(got, want / 4.0, rtol=1e-4)


# ------------------------------ index_mul_2d --------------------------------

def test_index_mul_2d():
    in1 = jax.random.normal(jax.random.PRNGKey(8), (10, 4))
    in2 = jax.random.normal(jax.random.PRNGKey(9), (6, 4))
    idx = jnp.array([0, 3, 3, 9, 1, 0])
    got = index_mul_2d(in1, in2, idx)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(in1)[np.asarray(idx)]
                               * np.asarray(in2), rtol=1e-6)

    def loss(a, b):
        return jnp.sum(jnp.sin(index_mul_2d(a, b, idx)))

    g1 = jax.grad(loss, argnums=(0, 1))(in1, in2)
    g2 = jax.grad(lambda a, b: jnp.sum(jnp.sin(
        jnp.take(a, idx, 0) * b)), argnums=(0, 1))(in1, in2)
    for a, e in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-5, atol=1e-6)


# ------------------------------ transducer ----------------------------------

def _rnnt_dp(log_probs, labels, T, U, blank=0):
    """Numpy alpha DP oracle (standard RNN-T forward variable)."""
    lp = np.asarray(log_probs, np.float64)
    alpha = np.full((T, U + 1), -np.inf)
    alpha[0, 0] = 0.0
    for u in range(1, U + 1):
        alpha[0, u] = alpha[0, u - 1] + lp[0, u - 1, labels[u - 1]]
    for t in range(1, T):
        alpha[t, 0] = alpha[t - 1, 0] + lp[t - 1, 0, blank]
        for u in range(1, U + 1):
            a = alpha[t - 1, u] + lp[t - 1, u, blank]
            b = alpha[t, u - 1] + lp[t, u - 1, labels[u - 1]]
            alpha[t, u] = np.logaddexp(a, b)
    return -(alpha[T - 1, U] + lp[T - 1, U, blank])


def test_transducer_loss_vs_dp():
    B, T, U, V = 3, 5, 4, 7
    x = jax.random.normal(jax.random.PRNGKey(10), (B, T, U + 1, V))
    log_probs = jax.nn.log_softmax(x, axis=-1)
    labels = jax.random.randint(jax.random.PRNGKey(11), (B, U), 1, V)
    f_len = jnp.array([5, 4, 3])
    y_len = jnp.array([4, 3, 2])
    got = transducer_loss(log_probs, labels, f_len, y_len)
    for i in range(B):
        want = _rnnt_dp(np.asarray(log_probs[i]), np.asarray(labels[i]),
                        int(f_len[i]), int(y_len[i]))
        np.testing.assert_allclose(float(got[i]), want, rtol=1e-4,
                                   err_msg=f"sample {i}")


def test_transducer_loss_grad_finite():
    B, T, U, V = 2, 4, 3, 5
    x = jax.random.normal(jax.random.PRNGKey(12), (B, T, U + 1, V))
    labels = jax.random.randint(jax.random.PRNGKey(13), (B, U), 1, V)
    f_len = jnp.array([4, 4])
    y_len = jnp.array([3, 3])

    def loss(x):
        lp = jax.nn.log_softmax(x, axis=-1)
        return jnp.mean(transducer_loss(lp, labels, f_len, y_len))

    g = jax.grad(loss)(x)
    assert np.isfinite(np.asarray(g)).all()


def test_transducer_joint():
    f = jax.random.normal(jax.random.PRNGKey(14), (2, 4, 8))
    g = jax.random.normal(jax.random.PRNGKey(15), (2, 3, 8))
    joint = TransducerJoint(relu=True)
    h = joint(f, g)
    assert h.shape == (2, 4, 3, 8)
    want = np.maximum(np.asarray(f)[:, :, None] + np.asarray(g)[:, None],
                      0)
    np.testing.assert_allclose(np.asarray(h), want, rtol=1e-6)


def test_self_attn_padding_mask_2d_flash_route():
    """(B, Sk) padding masks route through the segment-id flash path;
    parity vs the 4-D dense-mask result."""
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
    mha = SelfMultiheadAttn(32, 4, dropout=0.0)
    p = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 2, 32))
    pad2d = jnp.zeros((2, 64), bool).at[:, 48:].set(True)
    mask4d = pad2d[:, None, None, :]
    out2d = mha.apply(p, x, mask=pad2d, use_pallas_override=True)
    out4d = mha.apply(p, x, mask=mask4d, use_pallas_override=True)
    np.testing.assert_allclose(np.asarray(out2d[:48]),
                               np.asarray(out4d[:48]),
                               rtol=1e-4, atol=1e-4)


def test_fmha_cu_seqlens_packing():
    """cu_seqlens facade ≡ the reference's varlen packing: packed rows
    match per-sequence attention."""
    from apex_tpu.contrib.fmha import FMHA
    from apex_tpu.ops.flash_attention import attention_reference
    h, d = 2, 16
    s1, s2, pad = 24, 32, 8
    S = s1 + s2 + pad
    qkv = jax.random.normal(jax.random.PRNGKey(3), (1, S, 3, h, d))
    out = FMHA(causal=True)(qkv, cu_seqlens=jnp.array([0, s1, s1 + s2]))
    q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
    ref1 = attention_reference(q[:, :, :s1], k[:, :, :s1], v[:, :, :s1],
                               causal=True)
    ref2 = attention_reference(q[:, :, s1:s1 + s2], k[:, :, s1:s1 + s2],
                               v[:, :, s1:s1 + s2], causal=True)
    got = out.transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(got[:, :, :s1]), np.asarray(ref1),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got[:, :, s1:s1 + s2]),
                               np.asarray(ref2), rtol=1e-4, atol=1e-4)
