"""The bench.py --only / _run_isolated harness (round 5): the ResNet
metric is measured in a fresh subprocess so HBM fragmentation from the
GPT/BERT metrics cannot depress it.  These tests pin the CLI contract
without touching a device: JSON plumbing, retry placement, and the
fallback semantics main() relies on."""

import os
import sys
import types

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def test_only_registry_retries_and_rounds(monkeypatch):
    """_ONLY wraps the measurement in _retry (a transient tunnel flake
    must not discard isolation — review r5) and rounds to 0.1."""
    calls = []

    def fake_resnet(on_tpu):
        calls.append(on_tpu)
        if len(calls) == 1:
            raise RuntimeError("remote_compile: response body closed")
        return 2345.6789

    monkeypatch.setattr(bench, "_resnet50_img_per_sec", fake_resnet)
    monkeypatch.setattr(bench.time, "sleep", lambda *_: None)
    out = bench._ONLY["resnet50_img_per_sec"](True)
    assert out == 2345.7
    assert calls == [True, True]  # transient error retried


def test_run_isolated_parses_last_json_line(monkeypatch):
    def fake_run(cmd, **kw):
        assert cmd[1].endswith("bench.py")
        assert cmd[2:] == ["--only", "resnet50_img_per_sec"]
        assert kw.get("check") is True
        return types.SimpleNamespace(
            stdout="WARNING: noisy plugin line\n"
                   '{"resnet50_img_per_sec": 2310.4}\n',
            returncode=0)

    # _run_isolated imports subprocess function-locally; patch the module
    import subprocess as sp
    monkeypatch.setattr(sp, "run", fake_run)
    assert bench._run_isolated("resnet50_img_per_sec") == 2310.4


def test_run_isolated_propagates_child_failure(monkeypatch):
    """A child that exits nonzero (e.g. --only on a CPU-fallback
    backend exits 3) must raise so main() records
    resnet50_isolated=false and measures in-process instead."""
    import subprocess as sp

    def fake_run(cmd, **kw):
        raise sp.CalledProcessError(3, cmd, stderr="backend is cpu")

    monkeypatch.setattr(sp, "run", fake_run)
    with pytest.raises(sp.CalledProcessError):
        bench._run_isolated("resnet50_img_per_sec")


def test_run_isolated_skips_trailing_log_lines(monkeypatch):
    """A plugin/absl log line printed AFTER the JSON must not defeat
    isolation (ADVICE r5): the parser scans in reverse for the first
    line that is a dict containing the metric."""
    import subprocess as sp

    def fake_run(cmd, **kw):
        return types.SimpleNamespace(
            stdout='{"resnet50_img_per_sec": 2310.4}\n'
                   "I0000 plugin shutdown notice\n"
                   "not json either\n",
            returncode=0)

    monkeypatch.setattr(sp, "run", fake_run)
    assert bench._run_isolated("resnet50_img_per_sec") == 2310.4


def test_run_isolated_no_json_raises(monkeypatch):
    import subprocess as sp

    def fake_run(cmd, **kw):
        return types.SimpleNamespace(stdout="only logs\n", returncode=0)

    monkeypatch.setattr(sp, "run", fake_run)
    with pytest.raises(ValueError, match="resnet50_img_per_sec"):
        bench._run_isolated("resnet50_img_per_sec")


def test_only_wrong_arity_exits_with_usage(monkeypatch, capsys):
    monkeypatch.setattr(sys, "argv", ["bench.py", "--only"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 2
    assert "usage" in capsys.readouterr().err


def test_only_unknown_metric_lists_choices(monkeypatch, capsys):
    monkeypatch.setattr(sys, "argv", ["bench.py", "--only", "nope"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "unknown metric nope" in err
    assert "resnet50_img_per_sec" in err


def test_only_valid_metric_on_cpu_backend_exits_3(monkeypatch, capsys):
    """The CPU-fallback hard-exit (3) must survive the new validation:
    the parent's fallback depends on it."""
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--only", "resnet50_img_per_sec"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 3


def test_kernel_smoke_reports_ok_and_failures(monkeypatch):
    import subprocess as sp

    def fake_run(cmd, **kw):
        assert cmd[1].endswith("tpu_kernel_smoke.py")
        return types.SimpleNamespace(
            stdout="OK   layer_norm\nFAIL xentropy: Boom\nFAILURES\n",
            returncode=1)

    monkeypatch.setattr(sp, "run", fake_run)
    ok, fails = bench._kernel_smoke()
    assert ok is False
    # per-kernel lines only — the "FAILURES: [...]" summary is excluded
    assert fails == ["FAIL xentropy: Boom"]

    def fake_ok(cmd, **kw):
        return types.SimpleNamespace(stdout="ALL OK\n", returncode=0)

    monkeypatch.setattr(sp, "run", fake_ok)
    ok, fails = bench._kernel_smoke()
    assert ok is True and fails == []


def test_timed_records_duration_even_on_error():
    """Per-metric wall clock (ISSUE 2 satellite): _timed stamps the
    durations dict on success AND on the error path (a 15-min OOM
    spiral must be visible in the BENCH trajectory), and the JSON gains
    monitor_schema_version for cross-round comparability."""
    durations = {}
    with bench._timed(durations, "ok"):
        pass
    with pytest.raises(RuntimeError):
        with bench._timed(durations, "boom"):
            raise RuntimeError("x")
    assert set(durations) == {"ok", "boom"}
    assert all(isinstance(v, float) and v >= 0 for v in durations.values())

    from apex_tpu import monitor
    assert isinstance(monitor.SCHEMA_VERSION, int)
