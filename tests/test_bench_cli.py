"""The bench.py --only / _run_isolated harness (round 5): the ResNet
metric is measured in a fresh subprocess so HBM fragmentation from the
GPT/BERT metrics cannot depress it.  These tests pin the CLI contract
without touching a device: JSON plumbing, retry placement, and the
fallback semantics main() relies on."""

import os
import sys
import types

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def test_only_registry_retries_and_rounds(monkeypatch):
    """_ONLY wraps the measurement in _retry (a transient tunnel flake
    must not discard isolation — review r5) and rounds to 0.1."""
    calls = []

    def fake_resnet(on_tpu):
        calls.append(on_tpu)
        if len(calls) == 1:
            raise RuntimeError("remote_compile: response body closed")
        return 2345.6789

    monkeypatch.setattr(bench, "_resnet50_img_per_sec", fake_resnet)
    monkeypatch.setattr(bench.time, "sleep", lambda *_: None)
    out = bench._ONLY["resnet50_img_per_sec"](True)
    assert out == 2345.7
    assert calls == [True, True]  # transient error retried


def test_run_isolated_parses_last_json_line(monkeypatch):
    def fake_run(cmd, **kw):
        assert cmd[1].endswith("bench.py")
        assert cmd[2:] == ["--only", "resnet50_img_per_sec"]
        assert kw.get("check") is True
        return types.SimpleNamespace(
            stdout="WARNING: noisy plugin line\n"
                   '{"resnet50_img_per_sec": 2310.4}\n',
            returncode=0)

    # _run_isolated imports subprocess function-locally; patch the module
    import subprocess as sp
    monkeypatch.setattr(sp, "run", fake_run)
    assert bench._run_isolated("resnet50_img_per_sec") == 2310.4


def test_run_isolated_propagates_child_failure(monkeypatch):
    """A child that exits nonzero (e.g. --only on a CPU-fallback
    backend exits 3) must raise so main() records
    resnet50_isolated=false and measures in-process instead."""
    import subprocess as sp

    def fake_run(cmd, **kw):
        raise sp.CalledProcessError(3, cmd, stderr="backend is cpu")

    monkeypatch.setattr(sp, "run", fake_run)
    with pytest.raises(sp.CalledProcessError):
        bench._run_isolated("resnet50_img_per_sec")


def test_run_isolated_skips_trailing_log_lines(monkeypatch):
    """A plugin/absl log line printed AFTER the JSON must not defeat
    isolation (ADVICE r5): the parser scans in reverse for the first
    line that is a dict containing the metric."""
    import subprocess as sp

    def fake_run(cmd, **kw):
        return types.SimpleNamespace(
            stdout='{"resnet50_img_per_sec": 2310.4}\n'
                   "I0000 plugin shutdown notice\n"
                   "not json either\n",
            returncode=0)

    monkeypatch.setattr(sp, "run", fake_run)
    assert bench._run_isolated("resnet50_img_per_sec") == 2310.4


def test_run_isolated_no_json_raises(monkeypatch):
    import subprocess as sp

    def fake_run(cmd, **kw):
        return types.SimpleNamespace(stdout="only logs\n", returncode=0)

    monkeypatch.setattr(sp, "run", fake_run)
    with pytest.raises(ValueError, match="resnet50_img_per_sec"):
        bench._run_isolated("resnet50_img_per_sec")


def test_only_wrong_arity_exits_with_usage(monkeypatch, capsys):
    monkeypatch.setattr(sys, "argv", ["bench.py", "--only"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 2
    assert "usage" in capsys.readouterr().err


def test_only_unknown_metric_lists_choices(monkeypatch, capsys):
    monkeypatch.setattr(sys, "argv", ["bench.py", "--only", "nope"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "unknown metric nope" in err
    assert "resnet50_img_per_sec" in err


def test_only_valid_metric_on_cpu_backend_exits_3(monkeypatch, capsys):
    """The CPU-fallback hard-exit (3) must survive the new validation:
    the parent's fallback depends on it."""
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--only", "resnet50_img_per_sec"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 3


def test_kernel_smoke_reports_ok_and_failures(monkeypatch):
    import subprocess as sp

    def fake_run(cmd, **kw):
        assert cmd[1].endswith("tpu_kernel_smoke.py")
        return types.SimpleNamespace(
            stdout="OK   layer_norm\nFAIL xentropy: Boom\nFAILURES\n",
            returncode=1)

    monkeypatch.setattr(sp, "run", fake_run)
    ok, fails = bench._kernel_smoke()
    assert ok is False
    # per-kernel lines only — the "FAILURES: [...]" summary is excluded
    assert fails == ["FAIL xentropy: Boom"]

    def fake_ok(cmd, **kw):
        return types.SimpleNamespace(stdout="ALL OK\n", returncode=0)

    monkeypatch.setattr(sp, "run", fake_ok)
    ok, fails = bench._kernel_smoke()
    assert ok is True and fails == []


# --------------------------- bench_diff.py ---------------------------

def _bench_diff():
    """Import scripts/bench_diff.py as a module (the scripts dir is
    not a package — load by path, the engine is pure)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "bench_diff.py")
    spec = importlib.util.spec_from_file_location("bench_diff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_diff_direction_table():
    """Direction-aware verdicts (ISSUE 15 satellite): tokens/s down =
    regress, p99 up = regress, busy fraction up = improve — and a
    metric with no known polarity gets NO verdict, never a guess."""
    bd = _bench_diff()
    assert bd.metric_direction("gpt1p3b_tokens_per_sec_per_chip") == 1
    assert bd.metric_direction("value") == 1
    assert bd.metric_direction("serve_goodput_tokens_per_sec") == 1
    assert bd.metric_direction("serve_p99_ms") == -1
    assert bd.metric_direction("adam_1b_step_ms") == -1
    assert bd.metric_direction("ckpt_blocking_s") == -1
    assert bd.metric_direction("timeline_host_gap_ms") == -1
    assert bd.metric_direction("timeline_device_busy_fraction") == 1
    assert bd.metric_direction("moe_drop_fraction") == -1
    assert bd.metric_direction("comms_comm_fraction") == -1
    assert bd.metric_direction("baseline_batch") == 0
    assert bd.metric_direction("serve_pool_util_peak") == 0


def test_bench_diff_engine_thresholds_and_bools():
    bd = _bench_diff()
    old = {"value": 100.0, "serve_p99_ms": 10.0, "lint_ok": True,
           "mystery_number": 5.0, "gone_metric": 1.0}
    new = {"value": 90.0, "serve_p99_ms": 10.4, "lint_ok": False,
           "mystery_number": 50.0, "new_metric": 2.0}
    res = bd.diff_metrics(old, new, threshold_pct=5.0)
    by = {r["metric"]: r for r in res["rows"]}
    assert by["value"]["verdict"] == "REGRESS"          # -10% tokens/s
    assert by["serve_p99_ms"]["verdict"] == "ok"        # +4% < 5%
    assert by["lint_ok"]["verdict"] == "REGRESS"        # True -> False
    assert by["mystery_number"]["verdict"] == "n/a"     # no polarity
    assert res["only_in_new"] == ["new_metric"]
    assert res["only_in_old"] == ["gone_metric"]
    assert set(res["regressions"]) == {"value", "lint_ok"}
    assert not res["ok"]
    # a verdict FLAG vanishing must be listed, never silently dropped
    # (review fix): bool on one side only lands in only_in_*
    res_b = bd.diff_metrics({"comms_overlap_ok": True, "value": 1.0},
                            {"value": 1.0, "new_flag": False},
                            threshold_pct=5.0)
    assert res_b["only_in_old"] == ["comms_overlap_ok"]
    assert res_b["only_in_new"] == ["new_flag"]
    # a wider threshold absorbs the drop
    res2 = bd.diff_metrics(old, new, threshold_pct=15.0)
    assert "value" not in res2["regressions"]


def test_bench_diff_cli_selftest_and_exit_codes(tmp_path):
    """The committed mini-fixtures drive --selftest (drift gate), and
    the CLI exits nonzero exactly when a regression survived the
    threshold."""
    import json
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(root, "scripts", "bench_diff.py")
    r = subprocess.run([sys.executable, script, "--selftest"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "bench_diff --selftest: OK" in r.stdout
    # fixture A -> B: the seeded regressions exit 1 and are named
    fa = os.path.join(root, "scripts", "bench_diff_fixture_a.json")
    fb = os.path.join(root, "scripts", "bench_diff_fixture_b.json")
    r2 = subprocess.run([sys.executable, script, fa, fb],
                        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 1
    assert "REGRESS" in r2.stdout and "serve_p99_ms" in r2.stdout
    # identical files diff clean, exit 0 — and the BENCH_r* driver
    # wrapper ("parsed") unwraps
    wrapped = tmp_path / "w.json"
    wrapped.write_text(json.dumps(
        {"n": 1, "rc": 0, "parsed": json.load(open(fa))}))
    r3 = subprocess.run([sys.executable, script, str(wrapped), fa],
                        capture_output=True, text=True, timeout=120)
    assert r3.returncode == 0, r3.stdout + r3.stderr
    assert "no regression" in r3.stdout


def test_timed_records_duration_even_on_error():
    """Per-metric wall clock (ISSUE 2 satellite): _timed stamps the
    durations dict on success AND on the error path (a 15-min OOM
    spiral must be visible in the BENCH trajectory), and the JSON gains
    monitor_schema_version for cross-round comparability."""
    durations = {}
    with bench._timed(durations, "ok"):
        pass
    with pytest.raises(RuntimeError):
        with bench._timed(durations, "boom"):
            raise RuntimeError("x")
    assert set(durations) == {"ok", "boom"}
    assert all(isinstance(v, float) and v >= 0 for v in durations.values())

    from apex_tpu import monitor
    assert isinstance(monitor.SCHEMA_VERSION, int)
