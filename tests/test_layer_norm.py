"""Fused LayerNorm/RMSNorm parity tests.

≡ tests/L0/run_fused_layer_norm/test_fused_layer_norm.py — fused kernel
vs reference math over dtype × shape grids, fwd and bwd.  The Pallas
path runs in interpret mode on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.layer_norm import (
    FusedLayerNorm,
    FusedRMSNorm,
    fused_layer_norm,
    fused_rms_norm,
    layer_norm_reference,
    rms_norm_reference,
)

SHAPES = [(4, 16), (3, 5, 96), (17, 128)]


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(
        rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("affine", [True, False])
def test_layer_norm_forward(shape, dtype, affine):
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, shape, dtype)
    h = shape[-1]
    w = jax.random.normal(jax.random.PRNGKey(1), (h,), dtype) if affine else None
    b = jax.random.normal(jax.random.PRNGKey(2), (h,), dtype) if affine else None
    got = fused_layer_norm(x, w, b, use_pallas_override=True)
    want = layer_norm_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), **_tol(dtype))


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("affine", [True, False])
def test_layer_norm_grads(shape, affine):
    k = jax.random.PRNGKey(3)
    x = jax.random.normal(k, shape, jnp.float32)
    h = shape[-1]
    w = jnp.ones((h,)) * 1.5 if affine else None
    b = jnp.ones((h,)) * 0.5 if affine else None

    def loss_fused(x, w, b):
        y = fused_layer_norm(x, w, b, use_pallas_override=True)
        return jnp.sum(jnp.sin(y))

    def loss_ref(x, w, b):
        return jnp.sum(jnp.sin(layer_norm_reference(x, w, b)))

    if affine:
        g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    else:
        g1 = (jax.grad(loss_fused)(x, w, b),)
        g2 = (jax.grad(loss_ref)(x, w, b),)
    for a, e in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("shape", SHAPES)
def test_rms_norm(shape):
    x = jax.random.normal(jax.random.PRNGKey(4), shape, jnp.float32)
    w = jnp.full((shape[-1],), 1.2)
    got = fused_rms_norm(x, w, use_pallas_override=True)
    want = rms_norm_reference(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    def loss(x, w):
        return jnp.sum(jnp.cos(fused_rms_norm(x, w, use_pallas_override=True)))

    def loss_ref(x, w):
        return jnp.sum(jnp.cos(rms_norm_reference(x, w)))

    g1 = jax.grad(loss, argnums=(0, 1))(x, w)
    g2 = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    for a, e in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-4)


def test_modules():
    ln = FusedLayerNorm(64)
    params = ln.init()
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 64))
    y = ln.apply(params, x, use_pallas_override=True)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(layer_norm_reference(
            x, params["weight"], params["bias"])), rtol=1e-5, atol=1e-5)

    rn = FusedRMSNorm(64)
    p2 = rn.init()
    y2 = rn.apply(p2, x, use_pallas_override=True)
    np.testing.assert_allclose(
        np.asarray(y2), np.asarray(rms_norm_reference(x, p2["weight"])),
        rtol=1e-5, atol=1e-5)
