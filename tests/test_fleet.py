"""Fleet fault tolerance (ISSUE 11): multi-host checkpoint commit
(sub-manifest → rank-0 barrier protocol, stale-race resolution, prune
safety), the elastic-resume orchestration loop (recovery cycle,
escalation paths, retry/backoff), watchdog flap recovery, the
multiproc launcher's failure propagation, schema-v8 telemetry stamps,
and the `scripts/fleet_probe.py` CI gates (fixture selftest + a real
2-process × 2-device kill/resume smoke)."""

import json
import os
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

from apex_tpu.checkpoint import (
    CheckpointManager,
    ElasticOrchestrator,
    EscalationError,
    IncompleteCheckpointError,
    MultihostCommitError,
    RetryPolicy,
    chaos,
    latest_committed_step,
    load_model_state,
    pack_model_state,
    read_manifest,
    restore_sharded,
    unpack_model_state,
    verify_shards,
)
from apex_tpu.checkpoint import multihost as MH
from apex_tpu.checkpoint import sharded as S
from apex_tpu.checkpoint.chaos import LostRankWatchdog, RankLostError
from apex_tpu.monitor.trace.straggler import StragglerDetector

ROOT = pathlib.Path(__file__).resolve().parent.parent

LAYOUT4 = {"align": 1, "total": 16, "n_tensors": 2, "num_shards": 4,
           "n_buckets": 1, "bucket_totals": [16], "bucket_padded": [16],
           "master_dtype": "float32"}
FLAT = np.arange(16, dtype=np.float32)
SHARDS = {r: FLAT[r * 4:(r + 1) * 4] for r in range(4)}


def _commit_two_hosts(tmp, step, *, attempt=0, model_state=None):
    """Host 1 writes ranks 2-3, host 0 writes ranks 0-1 (+replicated)
    and commits.  Returns (step_dir, barrier_s)."""
    MH.save_sharded_multihost(
        tmp, step,
        {"params_shard": ("sharded", {2: SHARDS[2], 3: SHARDS[3]})},
        process_id=1, num_processes=2, attempt=attempt,
        flat_layout=LAYOUT4)
    fields = {"params_shard": ("sharded", {0: SHARDS[0], 1: SHARDS[1]}),
              "count": ("replicated", np.asarray(step, np.int64))}
    if model_state:
        fields.update(pack_model_state(model_state))
    return MH.save_sharded_multihost(
        tmp, step, fields, process_id=0, num_processes=2,
        attempt=attempt, flat_layout=LAYOUT4, timeout_s=10.0)


# ---------------------------------------------------------------------------
# multi-host commit protocol
# ---------------------------------------------------------------------------

def test_multihost_commit_atomicity_and_merge(tmp_path):
    """A sub-manifest alone is INVISIBLE; the rank-0 global manifest is
    the single source of truth; the merged manifest restores the
    canonical flat bitwise and carries model state + barrier stamp."""
    tmp = str(tmp_path)
    MH.save_sharded_multihost(
        tmp, 5,
        {"params_shard": ("sharded", {2: SHARDS[2], 3: SHARDS[3]})},
        process_id=1, num_processes=2, flat_layout=LAYOUT4)
    assert latest_committed_step(tmp) is None  # half-fleet: invisible
    p, barrier_s = _commit_two_hosts(
        tmp, 5, model_state={"rng": np.asarray([1, 2], np.uint32),
                             "bn": {"mean": np.ones(3, np.float32)}})
    assert barrier_s >= 0.0
    assert latest_committed_step(tmp) == 5
    verify_shards(p)  # single-host validation reads the merged manifest
    m = read_manifest(p)
    assert m["multihost"] == {"num_processes": 2, "hosts": [0, 1]}
    host = S.load_field_host(p, m, "params_shard", check_crc=True)
    assert np.array_equal(S.canonical_flat(host, LAYOUT4), FLAT)
    ms = load_model_state(tmp, 5)
    assert np.array_equal(ms["rng"], [1, 2])
    assert np.array_equal(ms["bn"]["mean"], np.ones(3))


def test_multihost_barrier_refuses_missing_and_stale(tmp_path):
    """The barrier times out REFUSING (named host) on a missing
    sub-manifest, and a stale attempt token is never mixed in."""
    tmp = str(tmp_path)
    d = S.step_dir(tmp, 7)
    sub = MH.write_host_shards(
        d, 7, {"params_shard": ("sharded", {0: SHARDS[0]})},
        host=0, num_processes=2)
    MH.publish_submanifest(d, sub)
    with pytest.raises(MultihostCommitError, match="host 1.*no sub"):
        MH.gather_submanifests(d, 2, step=7, timeout_s=0.2, poll_s=0.02)
    assert latest_committed_step(tmp) is None
    # stale attempt: host 0 published attempt 0; a retry at attempt 1
    # must not accept it
    with pytest.raises(MultihostCommitError, match="attempt 0 != 1"):
        MH.gather_submanifests(d, 1, step=7, attempt=1, timeout_s=0.2,
                               poll_s=0.02)
    # crc skew (a write in flight / torn file) is not-ready, → refusal
    fn = sub["fields"]["params_shard"]["files"][0]["file"]
    with open(os.path.join(d, fn), "r+b") as f:
        f.write(b"\xff\xff")
    with pytest.raises(MultihostCommitError, match="crc mismatch"):
        MH.gather_submanifests(d, 1, step=7, timeout_s=0.2, poll_s=0.02)


def test_multihost_merge_coverage_teeth(tmp_path):
    """Rank overlap, rank gaps, and non-rank-0 replicated fields are
    refused by name — a torn fleet never merges."""
    sub0 = MH.write_host_shards(
        S.step_dir(str(tmp_path), 1), 1,
        {"p": ("sharded", {0: SHARDS[0], 1: SHARDS[1]})},
        host=0, num_processes=2)
    dup = MH.write_host_shards(
        S.step_dir(str(tmp_path), 2), 1,
        {"p": ("sharded", {1: SHARDS[1], 2: SHARDS[2], 3: SHARDS[3]})},
        host=1, num_processes=2)
    with pytest.raises(MultihostCommitError, match="rank 1.*two hosts"):
        MH.merge_submanifests([sub0, dup], step=1, num_shards=4)
    with pytest.raises(MultihostCommitError, match="missing"):
        MH.merge_submanifests([sub0], step=1, num_shards=4)
    with pytest.raises(ValueError, match="rank-0 state"):
        MH.write_host_shards(
            S.step_dir(str(tmp_path), 3), 1,
            {"c": ("replicated", np.zeros(2))}, host=1, num_processes=2)


def test_stale_submanifest_race_resolves_to_committed_step(tmp_path):
    """Satellite: a straggler host's stale step_{k+1} directory (shards
    + sub-manifest, NO global manifest) next to a committed step k
    resolves to k on every host, and prune never deletes the in-flight
    staging directory of a NEWER step another host is still writing."""
    tmp = str(tmp_path)
    _commit_two_hosts(tmp, 4)
    # host 1 raced ahead: its half of step 5 is on disk, host 0 never
    # committed (died / still writing)
    MH.save_sharded_multihost(
        tmp, 5,
        {"params_shard": ("sharded", {2: SHARDS[2], 3: SHARDS[3]})},
        process_id=1, num_processes=2, flat_layout=LAYOUT4)
    assert latest_committed_step(tmp) == 4  # on every host: disk truth
    # restore resolves to the committed step, not the stale partial
    m = read_manifest(S.step_dir(tmp, 4))
    assert m["step"] == 4
    # prune keeps the newest commit AND host 1's in-flight step 5
    S.prune(tmp, keep=1)
    assert latest_committed_step(tmp) == 4
    assert os.path.exists(
        MH.submanifest_path(S.step_dir(tmp, 5), 1))
    # once step 5 commits, a later prune may clear step 4 — and the
    # stale-looking sub-manifests of COMMITTED steps stay harmless
    _, _ = MH.save_sharded_multihost(
        tmp, 5, {"params_shard": ("sharded",
                                  {0: SHARDS[0], 1: SHARDS[1]}),
                 "count": ("replicated", np.asarray(5, np.int64))},
        process_id=0, num_processes=2, flat_layout=LAYOUT4,
        timeout_s=10.0)
    assert latest_committed_step(tmp) == 5
    S.prune(tmp, keep=1)
    assert latest_committed_step(tmp) == 5
    assert not os.path.isdir(S.step_dir(tmp, 4))


def test_multihost_overwrite_refused(tmp_path):
    tmp = str(tmp_path)
    _commit_two_hosts(tmp, 2)
    with pytest.raises(S.CheckpointError, match="multi-host overwrite"):
        MH.save_sharded_multihost(
            tmp, 2, {"params_shard": ("sharded", {0: SHARDS[0]})},
            process_id=0, num_processes=2, flat_layout=LAYOUT4)


# ---------------------------------------------------------------------------
# CheckpointManager in multi-host mode (stub optimizer, no jit)
# ---------------------------------------------------------------------------

class _StubZeRO:
    """state_partition_specs/shard_layout of a 4-shard flat optimizer
    without any device work — exercises the manager's snapshot split."""
    num_shards = 4
    axis_name = "dp"

    def state_partition_specs(self):
        from jax.sharding import PartitionSpec as P
        return {"params_shard": P("dp"), "count": P()}

    def shard_layout(self):
        return dict(LAYOUT4)


def test_manager_multihost_split_and_stats(tmp_path):
    """Each host's manager writes only its local ranks + process 0 the
    replicated fields; process 0 stamps ckpt_commit_barrier_s; the
    committed manifest restores and model state round-trips."""
    tmp = str(tmp_path)
    state = {"params_shard": FLAT.copy(),
             "count": np.asarray(3, np.int64)}
    m1 = CheckpointManager(tmp, _StubZeRO(), every_n_steps=1,
                           process_id=1, num_processes=2,
                           async_write=False, barrier_timeout_s=10.0)
    m1.save(3, state)
    assert latest_committed_step(tmp) is None
    assert "ckpt_commit_barrier_s" not in m1.stats()
    m0 = CheckpointManager(tmp, _StubZeRO(), every_n_steps=1,
                           process_id=0, num_processes=2,
                           async_write=False, barrier_timeout_s=10.0)
    m0.save(3, state, model_state={"rng_key": np.asarray([9], np.uint32)})
    assert latest_committed_step(tmp) == 3
    st = m0.stats()
    assert st["ckpt_commit_barrier_s"] >= 0.0
    assert st["ckpt_last_step"] == 3
    p = S.step_dir(tmp, 3)
    m = read_manifest(p)
    # file set: 4 rank files (2 per host) + replicated count + model
    files = sorted(f["file"] for e in m["fields"].values()
                   for f in e["files"])
    assert files == ["count.bin", "model.rng_key.bin",
                     "params_shard.rank000.bin", "params_shard.rank001.bin",
                     "params_shard.rank002.bin", "params_shard.rank003.bin"]
    host = S.load_field_host(p, m, "params_shard")
    assert np.array_equal(S.canonical_flat(host, LAYOUT4), FLAT)
    assert np.array_equal(m0.restore_model_state(3)["rng_key"], [9])
    # restore_sharded never leaks model.* fields into optimizer state
    stub = _StubZeRO()
    restored, scaler, manifest = restore_sharded(tmp, stub)
    assert set(restored) == {"params_shard", "count"}
    assert np.array_equal(np.asarray(restored["params_shard"]), FLAT)


def test_manager_env_fallback_per_field(tmp_path, monkeypatch):
    """Each launcher id falls back to the env INDEPENDENTLY: passing
    only num_processes must still pick up APEX_TPU_PROCESS_ID, or
    every host believes it is process 0 (review finding)."""
    monkeypatch.setenv("APEX_TPU_PROCESS_ID", "1")
    monkeypatch.setenv("APEX_TPU_NUM_PROCESSES", "2")
    m = CheckpointManager(str(tmp_path), _StubZeRO(), num_processes=2)
    assert (m.process_id, m.num_processes) == (1, 2)
    m = CheckpointManager(str(tmp_path), _StubZeRO(), process_id=0)
    assert (m.process_id, m.num_processes) == (0, 2)
    m = CheckpointManager(str(tmp_path), _StubZeRO())
    assert (m.process_id, m.num_processes) == (1, 2)


def test_merge_refuses_unknown_shard_count():
    """Without num_shards/flat_layout the merge must REFUSE rather
    than guess n from the highest rank seen — a missing-tail-rank torn
    fleet would otherwise commit as 'complete' (review finding)."""
    import tempfile
    import shutil
    tmp = tempfile.mkdtemp()
    try:
        sub = MH.write_host_shards(
            S.step_dir(tmp, 1), 1,
            {"p": ("sharded", {0: SHARDS[0], 1: SHARDS[1]})},
            host=0, num_processes=2)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    with pytest.raises(MultihostCommitError,
                       match="cannot validate rank coverage"):
        MH.merge_submanifests([sub], step=1)


def test_manager_sync_failure_not_resurfaced(tmp_path):
    """A synchronous save that raised (refused barrier) must not leave
    a stale error behind: the NEXT save's wait() re-raising it would
    silently skip that write — a recovered fleet would lose its next
    resume point (review finding, reproduced)."""
    tmp = str(tmp_path)
    state = {"params_shard": FLAT.copy(),
             "count": np.asarray(1, np.int64)}
    m0 = CheckpointManager(tmp, _StubZeRO(), process_id=0,
                           num_processes=2, async_write=False,
                           barrier_timeout_s=0.2)
    with pytest.raises(MultihostCommitError):
        m0.save(4, state)  # host 1 never publishes: refused
    # the failure was surfaced ABOVE; the next save must run —
    # host 1 publishes first this time, so step 8 commits
    m1 = CheckpointManager(tmp, _StubZeRO(), process_id=1,
                           num_processes=2, async_write=False)
    m1.save(8, state)
    m0.barrier_timeout_s = 10.0
    m0.save(8, state)
    assert latest_committed_step(tmp) == 8


def test_manager_multihost_attempt_token_isolation(tmp_path):
    """A retry of the same step must bump the attempt token: process
    0 at attempt 1 refuses host 1's stale attempt-0 sub-manifest."""
    tmp = str(tmp_path)
    state = {"params_shard": FLAT.copy(),
             "count": np.asarray(1, np.int64)}
    m1 = CheckpointManager(tmp, _StubZeRO(), process_id=1,
                           num_processes=2, async_write=False,
                           attempt=0)
    m1.save(6, state)
    m0 = CheckpointManager(tmp, _StubZeRO(), process_id=0,
                           num_processes=2, async_write=False,
                           attempt=1, barrier_timeout_s=0.3)
    with pytest.raises(MultihostCommitError, match="attempt 0 != 1"):
        m0.save(6, state)
    assert latest_committed_step(tmp) is None


# ---------------------------------------------------------------------------
# watchdog flap recovery + orchestrator loop
# ---------------------------------------------------------------------------

def _timings(dp, slow_rank=None, skew=3.0):
    t = np.full((dp, 1), 0.1)
    if slow_rank is not None:
        t[slow_rank, 0] = 0.1 * skew
    return t


def test_watchdog_flap_recovery_resets_counter():
    """Satellite: a rank that recovers (skew back under threshold)
    resets to ZERO consecutive flags — it is never left one slow step
    away from a spurious RankLostError."""
    det = StragglerDetector(threshold=1.5, patience=1)
    wd = LostRankWatchdog(det, deadline=4)
    for _ in range(3):                       # deadline-1 slow steps
        wd.check(_timings(4, slow_rank=2))
    wd.check(_timings(4))                    # recovery step
    wd.check(_timings(4, slow_rank=2))       # slow again: counter is 1
    assert det._consecutive[2] == 1          # reset actually happened
    # without recovery the 4th consecutive flag raises, with the rank
    # and resume point carried structurally
    for _ in range(2):
        wd.check(_timings(4, slow_rank=2))
    with pytest.raises(RankLostError) as ei:
        wd.check(_timings(4, slow_rank=2))
    assert ei.value.rank == 2
    assert ei.value.last_committed is None


def test_watchdog_stale_summary_and_reset():
    """check() judges each detector summary ONCE: polling between
    updates can neither re-raise on stale data nor double-count; and
    reset() clears history so an elastic dp change doesn't trip the
    detector's rank-count guard."""
    det = StragglerDetector(threshold=1.5, patience=1)
    wd = LostRankWatchdog(det, deadline=3)
    wd.check(_timings(4, slow_rank=1))
    wd.check(_timings(4, slow_rank=1))
    # two stale re-checks of the same summary: no count, no raise
    assert wd.check()["flagged"][0]["consecutive"] == 2
    assert wd.check() is not None
    with pytest.raises(RankLostError):
        wd.check(_timings(4, slow_rank=1))
    wd.reset()
    # a rank-count change after reset folds cleanly (dp=4 → dp=2)
    assert wd.check(_timings(2)) is not None


def test_orchestrator_recovery_cycle(tmp_path):
    """Lost rank → dump naming the resume point → rebuild at the
    surviving topology → resume: one full cycle with a committed
    checkpoint on disk, stats/events/watchdog-reset all observable."""
    tmp = str(tmp_path)
    S.save_sharded(tmp, 4, {"params_shard": (
        "sharded", list(np.split(FLAT, 4))),
        "count": ("replicated", np.asarray(4, np.int64))},
        flat_layout=LAYOUT4)

    dumps = []

    class _Recorder:
        def dump(self, reason, oom=False):
            dumps.append(reason)

    resets = []

    class _WD:
        def reset(self):
            resets.append(True)

    calls = []

    def build(dp, resume_step, attempt):
        calls.append((dp, resume_step, attempt))

        def session():
            if dp == 4:
                raise RankLostError("rank 3 lost", rank=3,
                                    last_committed=4)
            return f"done@dp{dp}"
        return session

    orch = ElasticOrchestrator(tmp, build, initial_dp=4,
                               choose_dp=lambda dp, e: 2,
                               recorder=_Recorder(), watchdog=_WD())
    assert orch.run() == "done@dp2"
    assert calls == [(4, 4, 0), (2, 4, 1)]
    assert orch.stats() == {"fleet_resumes": 1, "fleet_dp": 2}
    assert resets == [True]
    assert len(dumps) == 1 and "last committed checkpoint: step 4" in \
        dumps[0]
    assert orch.events[0]["kind"] == "rank_lost"
    assert orch.events[0]["rank"] == 3
    assert orch.events[0]["resume_step"] == 4


def test_orchestrator_escalation_paths(tmp_path):
    """Hard escalation by name: no committed checkpoint; resume budget
    exhausted; transient build failures past the retry policy (with
    backoff observable through the injected sleep)."""
    empty = str(tmp_path / "empty")
    os.makedirs(empty)

    def build_doomed(dp, resume_step, attempt):
        def session():
            raise RankLostError("rank 1 lost", rank=1)
        return session

    with pytest.raises(EscalationError, match="NO committed checkpoint"):
        ElasticOrchestrator(empty, build_doomed, initial_dp=2).run()

    ckpt = str(tmp_path / "ckpt")
    S.save_sharded(ckpt, 1, {"c": ("replicated", np.zeros(2))})
    with pytest.raises(EscalationError, match="resume budget exhausted"):
        ElasticOrchestrator(ckpt, build_doomed, initial_dp=4,
                            max_resumes=0).run()

    sleeps = []

    def build_flaky(dp, resume_step, attempt):
        raise ConnectionError("coordinator not up yet")

    with pytest.raises(EscalationError, match="transient errors"):
        ElasticOrchestrator(
            ckpt, build_flaky, initial_dp=2,
            retry=RetryPolicy(attempts=3, backoff_s=0.01),
            sleep=sleeps.append).run()
    assert sleeps == [0.01, 0.02]  # exponential backoff, attempts-1

    # a NON-transient build error propagates untouched
    def build_broken(dp, resume_step, attempt):
        raise ValueError("bad config")

    with pytest.raises(ValueError, match="bad config"):
        ElasticOrchestrator(ckpt, build_broken, initial_dp=2).run()


def test_orchestrator_transient_then_recovers(tmp_path):
    """One ConnectionError then a clean session: retried at the same
    topology, zero resumes spent."""
    ckpt = str(tmp_path)
    S.save_sharded(ckpt, 1, {"c": ("replicated", np.zeros(2))})
    tries = []

    def build(dp, resume_step, attempt):
        tries.append(dp)
        if len(tries) == 1:
            raise ConnectionError("transient")
        return lambda: "ok"

    orch = ElasticOrchestrator(ckpt, build, initial_dp=2,
                               retry=RetryPolicy(backoff_s=0.0),
                               sleep=lambda s: None)
    assert orch.run() == "ok"
    assert tries == [2, 2]
    assert orch.stats() == {"fleet_resumes": 0, "fleet_dp": 2}


# ---------------------------------------------------------------------------
# chaos env arming + multiproc launcher
# ---------------------------------------------------------------------------

def test_chaos_arm_from_env_proc_filtering():
    try:
        env = {"APEX_TPU_CHAOS": "host.before_barrier,rank.lost_at_step:3",
               "APEX_TPU_CHAOS_PROC": "1", "APEX_TPU_PROCESS_ID": "0"}
        assert chaos.arm_from_env(env) == []          # wrong process
        env["APEX_TPU_PROCESS_ID"] = "1"
        assert chaos.arm_from_env(env) == [
            ("host.before_barrier", 1), ("rank.lost_at_step", 3)]
        # armed for real: 3rd check fires
        chaos.check("rank.lost_at_step")
        chaos.check("rank.lost_at_step")
        with pytest.raises(chaos.SimulatedPreemption):
            chaos.check("rank.lost_at_step")
        with pytest.raises(chaos.SimulatedPreemption):
            chaos.check("host.before_barrier")
        # alternate var (the probe's save-time staging) + bad point
        assert chaos.arm_from_env({"X": "host.before_barrier"},
                                  var="X") == [("host.before_barrier", 1)]
        chaos.disarm_all()
        with pytest.raises(ValueError, match="unknown fail point"):
            chaos.arm_from_env({"APEX_TPU_CHAOS": "nope.nope"})
    finally:
        chaos.disarm_all()


def test_wait_fleet_propagates_first_failure_and_terminates():
    """Satellite: a dead child no longer leaves siblings hanging — the
    first nonzero exit propagates and the sleeper is terminated well
    before its own runtime."""
    from apex_tpu.parallel.multiproc import wait_fleet
    t0 = time.monotonic()
    procs = [
        subprocess.Popen([sys.executable, "-c",
                          "import time; time.sleep(60)"]),
        subprocess.Popen([sys.executable, "-c",
                          "import sys; sys.exit(7)"]),
    ]
    rc = wait_fleet(procs, timeout=30.0, grace=0.0)
    assert rc == 7
    assert all(p.poll() is not None for p in procs)
    assert time.monotonic() - t0 < 20.0


def test_wait_fleet_grace_lets_survivors_finish(tmp_path):
    """With grace, a surviving child completes its own work after a
    sibling dies (the fleet probe's commit-or-refuse observation)."""
    from apex_tpu.parallel.multiproc import wait_fleet
    marker = str(tmp_path / "survivor_done")
    procs = [
        subprocess.Popen([sys.executable, "-c", "import sys; sys.exit(5)"]),
        subprocess.Popen([sys.executable, "-c",
                          "import time, pathlib; time.sleep(0.6); "
                          f"pathlib.Path({marker!r}).write_text('ok')"]),
    ]
    rc = wait_fleet(procs, timeout=30.0, grace=15.0)
    assert rc == 5
    assert os.path.exists(marker)


def test_wait_fleet_timeout_kills_hung_fleet():
    from apex_tpu.parallel.multiproc import wait_fleet
    p = subprocess.Popen([sys.executable, "-c",
                          "import time; time.sleep(60)"])
    t0 = time.monotonic()
    assert wait_fleet([p], timeout=0.5, grace=0.0) == 124
    assert p.poll() is not None
    assert time.monotonic() - t0 < 15.0


# ---------------------------------------------------------------------------
# schema v8 stamps + model-state pack/unpack
# ---------------------------------------------------------------------------

def test_pack_unpack_model_state_roundtrip():
    tree = {"rng": np.asarray([1, 2], np.uint32),
            "bn": {"mean": np.ones(3), "var": np.zeros(3)}}
    packed = pack_model_state(tree)
    assert sorted(packed) == ["model.bn.mean", "model.bn.var",
                              "model.rng"]
    flat = {k: v for k, (_, v) in packed.items()}
    back = unpack_model_state(flat)
    assert np.array_equal(back["bn"]["var"], np.zeros(3))
    with pytest.raises(ValueError, match="contains"):
        pack_model_state({"a.b": np.zeros(1)})
    with pytest.raises(ValueError, match="empty dict"):
        pack_model_state({"a": {}})


def test_logger_stamps_fleet_and_barrier_fields(tmp_path):
    """MetricsLogger(fleet=orch) stamps fleet_resumes/fleet_dp and a
    multihost ckpt stats dict with ckpt_commit_barrier_s validates
    under schema v8."""
    import apex_tpu.monitor as monitor
    from apex_tpu.monitor.logger import validate_record

    class _Fleet:
        def stats(self):
            return {"fleet_resumes": 2, "fleet_dp": 3}

    class _Ckpt:
        def stats(self):
            return {"ckpt_blocking_s": 0.01, "ckpt_save_s": 0.02,
                    "ckpt_last_step": 7, "ckpt_bytes": 1024,
                    "ckpt_commit_barrier_s": 0.005}

    path = str(tmp_path / "m.jsonl")
    logger = monitor.MetricsLogger([monitor.JSONLSink(path)],
                                   fleet=_Fleet(), ckpt=_Ckpt())
    logger.log_step(monitor.init_metrics())
    logger.close()
    with open(path) as f:
        rec = json.loads(f.readline())
    assert rec["fleet_resumes"] == 2
    assert rec["fleet_dp"] == 3
    assert rec["ckpt_commit_barrier_s"] == 0.005
    validate_record(rec)
    # fleet_resume_ok (the bench stamp) is schema-legal too
    rec["fleet_resume_ok"] = True
    validate_record(rec)
    rec["fleet_resumes"] = None  # never-null contract
    with pytest.raises(ValueError):
        validate_record(rec)


def test_bench_fleet_cycle_stamps():
    """bench.py's protocol-level kill→resume cycle: refusal observed,
    one orchestrated resume, bitwise canonical — fleet_resume_ok."""
    import bench

    cycle = bench._fleet_cycle(False)
    assert cycle["refused_ok"] and cycle["resume_ok"]
    assert cycle["resumes"] == 1
    result = {}
    bench._stamp_fleet(result, cycle)
    assert result["fleet_resume_ok"] is True
    assert result["fleet_resumes"] == 1
    assert result["ckpt_commit_barrier_s"] >= 0.0


# ---------------------------------------------------------------------------
# the standing CI gates (scripts/fleet_probe.py)
# ---------------------------------------------------------------------------

def _run_script(path, *args, timeout=600, env_extra=None):
    return subprocess.run(
        [sys.executable, str(path), *args], capture_output=True,
        text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu", **(env_extra or {})})


def test_fleet_probe_selftest():
    """Fixture drift gate: the committed global/sub-manifest fixture
    still validates, re-merges, and the seeded half-published barrier
    is refused by name (the selftest's own negative control)."""
    r = _run_script(ROOT / "scripts" / "fleet_probe.py", "--selftest")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "fleet_probe --selftest: OK" in r.stdout


def test_fleet_kill_resume_smoke(tmp_path):
    """The tier-1 2-process × 2-device smoke: a REAL fleet commits a
    multi-host checkpoint, host 1 really dies at
    host.before_submanifest during a later save, the surviving process
    0 refuses the torn commit BY NAME, the committed step stays
    loadable — and an in-process dp=2 resume replays the remaining
    steps BITWISE against the survivor's trajectory."""
    sys.path.insert(0, str(ROOT / "scripts"))
    try:
        import fleet_probe as FP
    finally:
        sys.path.pop(0)
    from apex_tpu.parallel import multiproc

    ckpt = str(tmp_path / "ckpt")
    results = str(tmp_path / "results")
    os.makedirs(ckpt)
    os.makedirs(results)
    os.environ["APEX_TPU_CHAOS_SAVE"] = "host.before_submanifest"
    os.environ["APEX_TPU_CHAOS_PROC"] = "1"
    try:
        rc = multiproc.main([
            "--nproc", "2", "--devices-per-proc", "2",
            "--coordinator", "127.0.0.1:12461",
            "--timeout", "240", "--grace", "120",
            str(ROOT / "scripts" / "fleet_probe.py"), "--worker",
            "--ckpt-dir", ckpt, "--result-dir", results,
            "--steps", "4", "--save-at", "2", "--kill-at", "4",
            "--dp", "2", "--barrier-timeout", "4"])
    finally:
        os.environ.pop("APEX_TPU_CHAOS_SAVE", None)
        os.environ.pop("APEX_TPU_CHAOS_PROC", None)
    assert rc == FP.KILLED_RC  # host 1 really died
    # the commit of step 2 survived the kill; step 4 never tore
    assert latest_committed_step(ckpt) == 2
    verify_shards(S.step_dir(ckpt, 2))
    assert "rng_key" in load_model_state(ckpt, 2)
    # survivor (process 0) finished and REFUSED the torn commit by name
    with open(os.path.join(results, "proc0.json")) as f:
        surv = json.load(f)
    assert surv["refusal"] and "host 1" in surv["refusal"]
    assert not os.path.exists(os.path.join(results, "proc1.json"))
    assert surv["steady_recompiles"] == 0
    # in-process resume at the same dp: the replayed tail is BITWISE
    # the survivor's trajectory (the orchestrator path, equal topology)
    def build(dp, resume_step, attempt):
        seg = FP._build_segment(dp, ckpt, resume_step=resume_step)

        def session():
            cfg, batch = FP._config()
            losses, retraces, _ = FP._drive(
                seg, FP._make_batches(4, batch, cfg.seq_len,
                                      cfg.vocab_size),
                resume_step, 4)
            return losses, retraces
        return session

    losses, retraces = ElasticOrchestrator(ckpt, build,
                                           initial_dp=2).run()
    from apex_tpu.parallel import mesh as M
    M.destroy_model_parallel()
    assert retraces == 0
    assert losses == surv["losses"][2:]
