"""apex_tpu.moe — expert-parallel Mixture-of-Experts (ISSUE 13).

The coverage the ISSUE names: router top-k vs the dense reference
(fp32, ties pinned by index), dispatch/combine round-trip bitwise at
capacity_factor=inf, the MoE train step bitwise-equal to the dense GPT
step at n_experts=1/top_k=1, dp x ep grid parity against a
single-device oracle, aux-loss gradients finite under amp dynamic
scaling — plus the RecompileSentry zero-steady-recompile acceptance
gate, the ep-layout checkpoint refusal BY NAME, the all-to-all
roofline formula against MoE payload sizes, and the flight-recorder
moe taps.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import GPT, GPTConfig
from apex_tpu.models.moe_gpt import (
    MoEGPT,
    MoEGPTConfig,
    build_moe_train_step,
    moe_smoke_config,
)
from apex_tpu.moe import dispatch as D
from apex_tpu.moe import router as R
from apex_tpu.optimizers.distributed_fused_adam import DistributedFusedAdam
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M


def _tree_leaves_named(tree):
    import jax.tree_util as jtu
    return {jtu.keystr(p): np.asarray(v)
            for p, v in jtu.tree_flatten_with_path(tree)[0]}


# ------------------------------ router ------------------------------

def test_router_topk_matches_dense_reference():
    """Blocked path byte-identical to the dense reference at every
    block size; gates/probs/logits are fp32 regardless of input dtype."""
    x = jax.random.normal(jax.random.PRNGKey(0), (37, 16), jnp.bfloat16)
    wg = jax.random.normal(jax.random.PRNGKey(1), (16, 8),
                           jnp.bfloat16) * 0.1
    ref = R.topk_gates_dense(x, wg, 2)
    assert ref.probs.dtype == jnp.float32
    assert ref.gate.dtype == jnp.float32
    assert ref.logits.dtype == jnp.float32
    for blk in (8, 16, 64):
        out = R.topk_gates_blocked(x, wg, 2, blk)
        for f in ref._fields:
            assert np.array_equal(np.asarray(getattr(ref, f)),
                                  np.asarray(getattr(out, f))), (f, blk)


def test_router_ties_pinned_by_index():
    """Equal gate probabilities resolve to the LOWER expert index —
    routing must be backend-independent."""
    logits = jnp.zeros((5, 4), jnp.float32)  # all tied
    probs = jax.nn.softmax(logits, axis=-1)
    gate, idx = jax.lax.top_k(probs, 2)
    assert np.array_equal(np.asarray(idx),
                          np.tile([0, 1], (5, 1)))
    np.testing.assert_array_equal(np.asarray(gate), 0.25)


def test_router_tuner_op_byte_identical():
    """A tuned `moe_router` block_rows hit changes scheduling only —
    the tune/ contract (heuristic fallback == tuned, byte-identical)."""
    from apex_tpu import tune
    from apex_tpu.tune.search import forced

    x = jax.random.normal(jax.random.PRNGKey(0), (40, 16), jnp.float32)
    wg = jax.random.normal(jax.random.PRNGKey(1), (16, 4),
                           jnp.float32) * 0.1
    miss = R.topk_gates(x, wg, 2)          # cache miss -> dense path
    attrs = tune.moe_router_attrs(40, 4, 2, x.dtype)
    with forced("moe_router", attrs, {"block_rows": 16}):
        hit = R.topk_gates(x, wg, 2)
    for f in miss._fields:
        assert np.array_equal(np.asarray(getattr(miss, f)),
                              np.asarray(getattr(hit, f))), f


def test_expert_capacity_math():
    assert R.expert_capacity(64, 4, 2, float("inf")) == 64
    assert R.expert_capacity(64, 4, 2, 1.0) == 32
    # rounds up to the sublane, clamps to tokens
    assert R.expert_capacity(100, 8, 1, 1.0) % 8 == 0
    assert R.expert_capacity(10, 2, 1, 100.0) == 10
    with pytest.raises(ValueError):
        R.expert_capacity(64, 4, 2, 0.0)


# ------------------------- dispatch/combine -------------------------

def test_dispatch_combine_roundtrip_bitwise():
    """capacity_factor=inf, k=1, unit gates: scatter -> exchange(ep=1)
    -> combine reproduces every token bit-for-bit."""
    t, h, e = 24, 8, 4
    x = jax.random.normal(jax.random.PRNGKey(2), (t, h), jnp.float32)
    idx = jax.random.randint(jax.random.PRNGKey(3), (t, 1), 0, e)
    cap = R.expert_capacity(t, e, 1, float("inf"))
    dest, dropped = R.capacity_destinations(idx, e, cap)
    assert float(np.asarray(dropped).sum()) == 0.0
    buf = D.dispatch(x, dest, e, cap)
    xe = D.exchange_dispatch(buf, "ep", 1, e, cap)
    ybuf = D.exchange_combine(xe, "ep", 1, e, cap)
    y = D.combine(ybuf, dest, jnp.ones((t, 1), jnp.float32))
    assert np.array_equal(np.asarray(y), np.asarray(x))


def test_dispatch_combine_roundtrip_bitwise_ep2():
    """The same round trip THROUGH the ep all_to_all pair on a real
    dp=2 x ep=2 mesh — the exchange must be an exact inverse."""
    e, h = 4, 8
    mesh = M.initialize_model_parallel(expert_model_parallel_size=2,
                                       devices=jax.devices()[:4])

    def f(xs):
        t = xs.shape[0]
        idx = (jnp.arange(t)[:, None] * 3) % e
        cap = R.expert_capacity(t, e, 1, float("inf"))
        dest, _ = R.capacity_destinations(idx, e, cap)
        buf = D.dispatch(xs, dest, e, cap)
        xe = D.exchange_dispatch(buf, "ep", 2, e, cap)
        ybuf = D.exchange_combine(xe, "ep", 2, e, cap)
        return D.combine(ybuf, dest, jnp.ones((t, 1), jnp.float32))

    x = jax.random.normal(jax.random.PRNGKey(4), (16, h), jnp.float32)
    out = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(("dp", "ep")),),
                            out_specs=P(("dp", "ep")),
                            check_vma=False))(x)
    assert np.array_equal(np.asarray(out), np.asarray(x))


def test_capacity_dropping_routes_to_trash():
    """Over-capacity assignments land on the trash row and contribute
    exactly zero at combine; kept rows are untouched."""
    t, h, e, cap = 8, 4, 2, 2
    x = jnp.arange(t * h, dtype=jnp.float32).reshape(t, h) + 1.0
    idx = jnp.zeros((t, 1), jnp.int32)          # everyone wants expert 0
    dest, dropped = R.capacity_destinations(idx, e, cap)
    assert float(np.asarray(dropped).sum()) == t - cap
    assert np.all(np.asarray(dest[cap:, 0]) == e * cap)  # trash
    buf = D.dispatch(x, dest, e, cap)
    ybuf = D.exchange_combine(
        D.exchange_dispatch(buf, "ep", 1, e, cap), "ep", 1, e, cap)
    y = D.combine(ybuf, dest, jnp.ones((t, 1), jnp.float32))
    assert np.array_equal(np.asarray(y[:cap]), np.asarray(x[:cap]))
    assert np.all(np.asarray(y[cap:]) == 0.0)   # dropped -> zeros


# --------------------- the dense-GPT bitwise anchor ---------------------

def _map_dense_into_moe(dense_params, moe_params, n_layers):
    for i in range(n_layers):
        bp, dbp = moe_params[f"block{i}"], dense_params[f"block{i}"]
        bp["moe"]["w1"] = dbp["fc1"]["weight"][None]
        bp["moe"]["b1"] = dbp["fc1"]["bias"][None]
        bp["moe"]["w2"] = dbp["fc2"]["weight"][None]
        bp["moe"]["b2"] = dbp["fc2"]["bias"][None]


def test_moe_step_bitwise_equals_dense_gpt_step():
    """The acceptance anchor: at n_experts=1 / top_k=1 / cf=inf /
    aux=z=0 the full ZeRO-2 train step — loss AND every updated
    parameter — is bitwise the dense GPT step's, three steps deep."""
    kw = dict(vocab_size=512, seq_len=32, hidden=32, num_layers=2,
              num_heads=4, dropout=0.0)
    dense_cfg = GPTConfig(**kw)
    moe_cfg = MoEGPTConfig(n_experts=1, top_k=1,
                           capacity_factor=float("inf"),
                           aux_coef=0.0, z_coef=0.0, **kw)
    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])
    dense, moe = GPT(dense_cfg), MoEGPT(moe_cfg)
    dp_params = dense.init(jax.random.PRNGKey(0))
    mp_params = moe.init(jax.random.PRNGKey(0))
    _map_dense_into_moe(dp_params, mp_params, 2)

    def build(model, params, has_aux):
        opt = DistributedFusedAdam(num_shards=2, lr=1e-3, n_buckets=2)
        sspec = opt.state_partition_specs()
        state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                                  out_specs=sspec, check_vma=False))(
                                      params)
        if has_aux:
            def loss_fn(p, b):
                return model.loss_with_stats(p, b[0], b[1])
        else:
            def loss_fn(p, b):
                return model.loss(p, b[0], b[1])
        step = ddp.make_train_step(loss_fn, opt, mesh, has_aux=has_aux,
                                   batch_spec=(P("dp"), P("dp")))
        return opt, state, step

    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 512)
    labels = jnp.roll(tokens, -1, axis=1)
    opt_d, st_d, step_d = build(dense, dp_params, False)
    opt_m, st_m, step_m = build(moe, mp_params, True)
    for it in range(3):
        st_d, _, loss_d = step_d(st_d, None, (tokens, labels))
        st_m, _, loss_m, aux = step_m(st_m, None, (tokens, labels))
        assert np.array_equal(np.asarray(loss_d), np.asarray(loss_m)), \
            f"loss diverged at step {it}"
    assert float(aux["moe_drop_fraction"]) == 0.0
    assert float(aux["moe_aux_loss"]) == 1.0  # E=1: perfectly balanced

    def gather(opt, st):
        return jax.jit(shard_map(
            lambda s: opt.full_params(s), mesh=mesh,
            in_specs=(opt.state_partition_specs(),), out_specs=P(),
            check_vma=False))(st)

    ld = _tree_leaves_named(gather(opt_d, st_d))
    lm = _tree_leaves_named(gather(opt_m, st_m))
    for k in sorted(ld):
        km = (k.replace("fc1']['weight", "moe']['w1")
               .replace("fc1']['bias", "moe']['b1")
               .replace("fc2']['weight", "moe']['w2")
               .replace("fc2']['bias", "moe']['b2"))
        assert np.array_equal(ld[k], lm[km].reshape(ld[k].shape)), k


# ------------------------- dp x ep grid parity -------------------------

def test_dp_ep_grid_parity_vs_single_device_oracle():
    """dp=4 x ep=2 over 8 devices vs one device holding the whole
    batch: identical routing decisions (cf leaves no drops at these
    shapes), loss allclose (reduction order only)."""
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 64), 0, 512)
    labels = jnp.roll(tokens, -1, axis=1)

    cfg1 = moe_smoke_config(ep=1, aux_coef=0.0, z_coef=1e-3)
    mesh1 = M.initialize_model_parallel(devices=jax.devices()[:1])
    m1 = MoEGPT(cfg1)
    p1 = m1.init(jax.random.PRNGKey(0))
    loss1 = jax.jit(shard_map(
        lambda p, b: m1.loss(p, b[0], b[1]).reshape(1), mesh=mesh1,
        in_specs=(P(), (P(), P())), out_specs=P(),
        check_vma=False))(p1, (tokens, labels))

    M.destroy_model_parallel()
    cfg2 = moe_smoke_config(ep=2, aux_coef=0.0, z_coef=1e-3)
    mesh2 = M.initialize_model_parallel(expert_model_parallel_size=2)
    assert M.get_data_parallel_world_size() == 4
    m2 = MoEGPT(cfg2)
    p2 = m2.init(jax.random.PRNGKey(0))

    def dloss(p, b):
        return jax.lax.pmean(m2.loss(p, b[0], b[1]),
                             ("dp", "ep")).reshape(1)

    loss2 = jax.jit(shard_map(
        dloss, mesh=mesh2,
        in_specs=(P(), (P(("dp", "ep")), P(("dp", "ep")))),
        out_specs=P(), check_vma=False))(p2, (tokens, labels))
    np.testing.assert_allclose(float(loss1[0]), float(loss2[0]),
                               rtol=2e-5)


# ---------------------- the flagship train step ----------------------

def test_moe_train_step_zero_steady_recompiles():
    """The acceptance criterion: models/moe_gpt.py trains under
    ddp.make_train_step on a dp x ep CPU mesh with ZERO steady-state
    recompiles and a decreasing loss."""
    from apex_tpu.monitor.compile import RecompileSentry

    model, step, args, info = build_moe_train_step(False)
    assert info["ep"] == 2  # the 8-way test mesh always splits
    state, _, (tok_sds, _) = args
    tokens = jax.random.randint(jax.random.PRNGKey(1), tok_sds.shape,
                                0, info["vocab_size"])
    labels = jnp.roll(tokens, -1, axis=1)
    sentry = RecompileSentry(step, name="moe_gpt", warn=False)
    losses = []
    for i in range(4):
        state, _, loss, aux = sentry(state, None, (tokens, labels))
        losses.append(float(loss))
        if i == 0:
            sentry.mark_steady()
    assert sentry.steady_recompiles == 0, sentry.events
    assert losses[-1] < losses[0]
    for k, v in aux.items():
        assert math.isfinite(float(v)), (k, float(v))


def test_aux_loss_grad_finite_under_amp_dynamic_scaling():
    """Aux-loss gradients (router path included) stay finite under
    dynamic loss scaling at the 2^16 initial scale; no overflow-skip
    fires on the smoke shapes."""
    from apex_tpu import amp

    cfg = moe_smoke_config(ep=1, aux_coef=1e-2, z_coef=1e-3)
    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])
    model = MoEGPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(num_shards=2, lr=1e-4, n_buckets=1)
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    amp_state = amp.initialize(opt_level="O1")
    scaler = amp_state.loss_scalers[0]

    def loss_fn(p, b):
        return model.loss_with_stats(p, b[0], b[1])

    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               has_aux=True,
                               batch_spec=(P("dp"), P("dp")),
                               metrics=True)
    from apex_tpu.monitor import init_metrics
    mstate = init_metrics()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0,
                                cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    for _ in range(3):
        state, scaler, loss, aux, mstate = step(
            state, scaler, (tokens, labels), mstate)
    assert math.isfinite(float(loss))
    for k, v in aux.items():
        assert math.isfinite(float(v)), k
    m = jax.device_get(mstate)
    assert math.isfinite(float(m.grad_norm)) and float(m.grad_norm) > 0
    assert int(m.overflow_count) == 0
    assert float(scaler.scale) == 2.0 ** 16


def test_moe_taps_ride_tap_state_plane():
    """The block{i}/moe taps (per-expert load / drop / gate entropy)
    flow through the existing TapState plane; the untapped step is
    numerically untouched."""
    model, step, args, info = build_moe_train_step(False, trace=True)
    state, _, (tok_sds, _) = args
    tokens = jax.random.randint(jax.random.PRNGKey(1), tok_sds.shape,
                                0, info["vocab_size"])
    labels = jnp.roll(tokens, -1, axis=1)
    state, _, loss, aux, tap_state = step(state, None, (tokens, labels))
    names = step.tap_names()
    for want in ("block0/moe/load", "block0/moe/drop",
                 "block1/moe/gate_entropy"):
        assert want in names
    st = jax.device_get(tap_state)
    load = st.fwd[names.index("block0/moe/load")]
    n_exp = info["config"].n_experts
    np.testing.assert_allclose(load[1], 1.0 / n_exp, rtol=1e-5)  # mean
    ent = st.fwd[names.index("block0/moe/gate_entropy")]
    assert 0 < ent[1] <= math.log(n_exp) + 1e-5

    _, step2, args2, _ = build_moe_train_step(False)
    st2, _, loss2, _ = step2(args2[0], None, (tokens, labels))
    assert np.array_equal(np.asarray(loss), np.asarray(loss2))


# ----------------------- checkpoint ep refusal -----------------------

def test_restore_refuses_ep_layout_by_name(tmp_path):
    """A dp=2 x ep=2 manifest must be REFUSED by a dp=4 dense target
    with a LayoutMismatchError naming the ep axis — never silently
    concatenated (the elastic re-shard contract is dp-only)."""
    from apex_tpu.checkpoint import sharded as S

    n, dp_ep = 64, 4
    layout = {"align": 1, "total": n, "n_tensors": 1,
              "num_shards": dp_ep, "n_buckets": 1,
              "bucket_totals": [n], "bucket_padded": [n],
              "master_dtype": "float32", "ep_shards": 2}
    flat = np.arange(n, dtype=np.float32)
    shards = [flat[r * n // dp_ep:(r + 1) * n // dp_ep]
              for r in range(dp_ep)]
    S.save_sharded(str(tmp_path), 3,
                   {"params_shard": ("sharded", shards)},
                   flat_layout=layout)

    dense_dst = dict(layout, num_shards=4)
    dense_dst.pop("ep_shards")
    with pytest.raises(S.LayoutMismatchError, match="'ep'|ep="):
        S.reshard(shards, layout, dense_dst)

    class FakeDenseOpt:
        axis_name = "dp"

        def shard_layout(self):
            return dense_dst

        _STATE = None

    with pytest.raises(S.LayoutMismatchError) as ei:
        S.restore_sharded(str(tmp_path), FakeDenseOpt())
    assert "ep" in str(ei.value)

    # the SAME ep layout restores fine (dp elasticity untouched)
    class FakeEpOpt(FakeDenseOpt):
        def shard_layout(self):
            return dict(layout)

    state, scaler, manifest = S.restore_sharded(str(tmp_path),
                                                FakeEpOpt())
    assert np.array_equal(np.asarray(state["params_shard"]), flat)


def test_moe_zero_state_checkpoint_roundtrip(tmp_path):
    """CheckpointManager saves the (dp, ep)-sharded flat state with
    ep_shards recorded in the manifest, and the same-topology restore
    is bitwise."""
    from apex_tpu.checkpoint import CheckpointManager

    model, step, args, info = build_moe_train_step(False)
    world = info["dp"] * info["ep"]
    opt = DistributedFusedAdam(num_shards=world, lr=1e-4, n_buckets=2,
                               axis_name=("dp", "ep"),
                               ep_shards=info["ep"])
    params = model.init(jax.random.PRNGKey(0))
    mesh = info["mesh"]
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    mgr = CheckpointManager(str(tmp_path), opt, every_n_steps=1)
    mgr.save(1, state)
    mgr.wait()
    from apex_tpu.checkpoint import sharded as S
    man = S.read_manifest(S.step_dir(str(tmp_path), 1))
    assert man["flat_layout"]["ep_shards"] == info["ep"]
    restored, _, _ = mgr.restore(mesh)
    for f in state._fields:
        assert np.array_equal(np.asarray(getattr(restored, f)),
                              np.asarray(getattr(state, f))), f


# ------------------------- comms roofline -------------------------

def test_all_to_all_roofline_formula_moe_payloads():
    """The ring all-to-all price ((n-1)/n * D / bw) against the real
    MoE exchange payload sizes: D = E * C * H * itemsize per
    direction."""
    from apex_tpu.monitor.comms.roofline import collective_seconds

    bw = 200e9
    for (e, cap, h, itemsize, ep) in (
            (8, 256, 1024, 2, 2),      # bench bf16 shape
            (8, 256, 1024, 2, 4),
            (4, 64, 64, 4, 2)):        # smoke fp32 shape
        payload = e * cap * h * itemsize
        got = collective_seconds("all-to-all", payload, ep, bw)
        want = (ep - 1) / ep * payload / bw
        assert got == pytest.approx(want, rel=1e-12)
    # degenerate ep=1 exchange costs nothing (and traces no collective)
    assert collective_seconds("all-to-all", 1 << 20, 1, bw) == 0.0


# ------------------------- telemetry plane -------------------------

def test_metrics_logger_stamps_moe_fields():
    """SCHEMA v9: `MetricsLogger(moe=recorder)` stamps the moe_*
    scalars once the trainer fed the recorder a step's aux; before
    that nothing is stamped (the OPTIONAL-never-null rule)."""
    from apex_tpu import monitor
    from apex_tpu.moe import MoEAux, MoERecorder

    assert monitor.SCHEMA_VERSION >= 9
    rec = MoERecorder()
    logger = monitor.MetricsLogger([], moe=rec)
    mstate = monitor.init_metrics()
    r1 = logger.log_step(mstate)
    assert "moe_aux_loss" not in r1  # nothing fed yet

    rec.update(MoEAux(aux_loss=jnp.float32(1.25),
                      z_loss=jnp.float32(0.5),
                      drop_fraction=jnp.float32(0.03),
                      gate_entropy=jnp.float32(1.1)))
    mstate = mstate._replace(step=mstate.step + 1)
    r2 = logger.log_step(mstate)
    assert r2["moe_aux_loss"] == 1.25
    assert r2["moe_drop_fraction"] == pytest.approx(0.03)
    assert r2["moe_gate_entropy"] == pytest.approx(1.1)
    monitor.validate_records([r1, r2])


# ------------------------- config validation -------------------------

def test_moe_config_validation():
    with pytest.raises(ValueError, match="sequence_parallel"):
        MoEGPTConfig(sequence_parallel=True)
    with pytest.raises(ValueError, match="remat"):
        MoEGPTConfig(remat=True)
    with pytest.raises(ValueError, match="divide"):
        MoEGPTConfig(n_experts=3, expert_parallel=2)
    with pytest.raises(ValueError, match="top_k"):
        from apex_tpu.moe.layer import MoEMLP
        MoEMLP(8, 32, 2, top_k=4)


def test_zero_optimizers_take_ep_shards():
    """BOTH ZeRO optimizers carry the ep annotation the checkpoint
    refusal keys on (a LAMB MoE run must be just as refusable)."""
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedLAMB,
    )
    opt = DistributedFusedLAMB(num_shards=4, axis_name=("dp", "ep"),
                               ep_shards=2)
    assert opt.ep_shards == 2
    with pytest.raises(ValueError, match="ep_shards"):
        DistributedFusedLAMB(num_shards=4, ep_shards=3)
    with pytest.raises(ValueError, match="ep_shards"):
        DistributedFusedAdam(num_shards=4, ep_shards=3)


def test_moe_refuses_tensor_parallel_mesh():
    """tp > 1 must raise LOUDLY at trace time (experts replicate over
    tp; the RowParallel-style reduce would scale outputs by tp)."""
    cfg = moe_smoke_config(ep=1)
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=2)
    model = MoEGPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0,
                                cfg.vocab_size)
    with pytest.raises(NotImplementedError, match="tensor parallelism"):
        jax.jit(shard_map(
            lambda p, t: model.loss(p, t, t), mesh=mesh,
            in_specs=(P(), P("dp")), out_specs=P(),
            check_vma=False)).lower(params, tokens)
