"""Test harness: emulated 8-device CPU mesh.

The reference tests distributed code by spawning ≤4 NCCL processes per
node (apex/transformer/testing/distributed_test_base.py:22-74).  The
TPU-native equivalent runs every test in ONE process against an 8-way
virtual CPU mesh via XLA's host-platform device-count flag — collectives
and shardings compile and execute exactly as on an 8-chip slice.
"""

import os

# Force CPU: the session environment pins JAX_PLATFORMS to the real TPU
# tunnel (axon) and pre-imports jax via sitecustomize, so env vars alone
# are too late — use jax.config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5) has no jax_num_cpu_devices option; the
    # XLA_FLAGS --xla_force_host_platform_device_count set above
    # (before the jax import) provides the 8-way virtual mesh there
    pass
jax.config.update("jax_enable_x64", False)
assert jax.device_count() == 8, jax.devices()

from apex_tpu import _compat  # noqa: E402,F401 — jax version shims
# (must run before test modules execute `from jax import shard_map`)

import pytest  # noqa: E402

# Smoke tier (≡ the reference's per-directory L0 subsets,
# tests/L0/run_test.py:19-34): ONE fast, meaningful test per subsystem,
# ~90 s serial on the virtual mesh.  `pytest -m smoke`.  The full suite
# (`pytest tests/`) is the L1 equivalent — ~30 min serial, documented in
# README.  Keep every entry under ~10 s; timings from --durations=0.
SMOKE = {
    # kernels
    "test_flash_attention.py::test_flash_grads[True]",
    "test_softmax.py::test_scaled_masked_softmax",
    "test_layer_norm.py::test_layer_norm_grads[True-shape0]",
    "test_xentropy.py::test_xent_grad[0.0]",
    "test_fused_dense_mlp.py::test_linear_gelu_linear",
    # optimizers
    "test_optimizers.py::test_fused_adam_vs_optax_adamw[0.0]",
    "test_distributed_optimizers.py::test_dist_adam_matches_fused_adam",
    # data parallel / amp
    "test_ddp.py::test_make_train_step_matches_full_batch",
    "test_ddp.py::test_make_train_step_with_amp_dynamic_scaling",
    "test_distributed_tier.py::TestSyncBNDistributed::"
    "test_syncbn_matches_global_bn",
    # model parallel
    "test_tensor_parallel_layers.py::test_column_parallel_linear",
    "test_mesh_collectives.py::test_copy_reduce_pair",
    "test_pipeline_parallel.py::test_pipeline_matches_sequential[4]",
    "test_schedules_common.py::TestSchedulesCommon::"
    "test_backward_step_chain_matches_full_grad",
    # long context
    "test_context_parallel.py::test_ring_attention_matches_dense[False]",
    # models end-to-end
    "test_gpt_minimal.py::test_gpt_trains_tp_dp",
    "test_bert_minimal.py::test_bert_trains_with_lamb",
    # contrib
    "test_contrib_ops.py::test_self_multihead_attn[False]",
    "test_contrib_ops.py::test_transducer_joint",
    "test_contrib_spatial.py::test_spatial_conv_matches_dense",
    "test_misc_components.py::test_rnn_cells[LSTM]",
    # aux subsystems
    "test_checkpoint.py::test_checkpoint_roundtrip",
    "test_host_runtime.py::test_flat_layout",
}


# L1 tier (≡ the reference's tests/L1 heavy suites): the measured-slow
# tests (≥14 s serial; durations from a full --durations run) that push
# the default run past the budget.  Most files keep lighter siblings in
# the default (L0) tier (the cross-product file is l1 wholesale; its
# default-tier coverage lives in test_amp_casts.py + the e2e model
# tests); `pytest -m l1` runs these.
L1 = {
    "test_context_parallel.py::test_ring_attention_128k_causal_fwd_bwd",
    "test_distributed_optimizers.py::"
    "test_dist_adam_100m_scale_and_state_roundtrip",
    "test_distributed_optimizers.py::test_dist_lamb_100m_scale",
    "test_examples.py::test_dcgan_runs[O1]",
    "test_examples.py::test_dcgan_runs[O2]",
    "test_examples.py::test_simple_distributed_runs",
    "test_examples.py::test_long_context_training_runs",
    "test_bert_minimal.py::test_bert_loss_consistent_across_tp",
    "test_bert_minimal.py::test_bert_flash_vs_dense_attention_parity",
    "test_bert_minimal.py::test_bert_pad_mask",
    # (all of test_l1_cross_product.py is l1 via its module-level
    # pytestmark — round 5 moved the parity half there too, restoring
    # the default tier's runtime margin)
    "test_gpt_pipelined.py::test_pipelined_matches_plain",
    "test_gpt_pipelined.py::test_pipelined_interleaved_matches",
    "test_gpt_pipelined.py::test_pipelined_grads_flow",
    "test_gpt_pipelined.py::"
    "test_pipelined_training_keeps_tied_embed_in_sync",
    "test_resnet_e2e.py::test_opt_level_parity",
    "test_resnet_e2e.py::test_resnet_trains[O0]",
    "test_resnet_e2e.py::test_resnet_trains[O1]",
    "test_optimizers.py::test_master_dtype_bf16_trains",
    "test_gpt_minimal.py::test_sequence_parallel_matches",
    "test_gpt_minimal.py::test_loss_consistent_across_tp",
    "test_gpt_minimal.py::test_init_loss_near_uniform",
    "test_gpt_minimal.py::test_train_step_cache_keys_on_shapes",
    "test_sync_batchnorm.py::test_syncbn_backward_matches_full_batch",
    "test_sync_batchnorm.py::test_syncbn_matches_full_batch",
    "test_tensor_parallel_layers.py::test_vocab_parallel_cross_entropy",
    "test_tensor_parallel_layers.py::test_column_row_mlp_pattern",
    "test_tensor_parallel_layers.py::test_sequence_parallel_mlp",
    "test_tensor_parallel_layers.py::test_vocab_parallel_embedding",
    "test_misc_components.py::"
    "test_permutation_search_subdivides_wide_matrices",
    "test_gpt_pipelined.py::test_pipelined_microbatch_count_invariance",
    "test_contrib_ops.py::test_transducer_loss_grad_finite",
    "test_contrib_ops.py::test_encdec_multihead_attn",
    "test_pipeline_parallel.py::test_pipeline_grads_match_sequential",
    "test_contrib_spatial.py::test_conv_bias_relu_and_fmha",
    "test_contrib_spatial.py::test_spatial_conv_grads",
    "test_contrib_spatial.py::test_groupbn_subgroup",
    "test_distributed_tier.py::"
    "TestDDPAnalyticGrads::test_bucketed_matches_plain",
    "test_flash_attention.py::test_flash_in_kernel_dropout_mask_consistency",
    "test_fused_dense_mlp.py::test_mlp_vs_sequential",
    "test_softmax.py::test_scaled_softmax[1.0-shape0]",
}

assert not (SMOKE & L1), "a test cannot be both smoke and l1"


def pytest_collection_modifyitems(config, items):
    matched = set()
    matched_l1 = set()
    for item in items:
        key = item.nodeid.rsplit("tests/", 1)[-1]
        if key in SMOKE:
            matched.add(key)
            item.add_marker(pytest.mark.smoke)
        if key in L1:
            matched_l1.add(key)
            item.add_marker(pytest.mark.l1)
    missing = (SMOKE - matched) | (L1 - matched_l1)
    # fail loudly when a rename/reparametrize silently drops a smoke/l1
    # entry — but only when the whole suite was collected (a -k/-m or
    # path-restricted run legitimately sees a subset; the addopts
    # default of -m "not l1" deselects AFTER collection, so every item
    # is still visible here)
    unrestricted = (
        not config.getoption("keyword", default="")
        and config.getoption("markexpr", default="") in ("", "not l1")
        and not config.getoption("ignore", default=None)
        and not config.getoption("ignore_glob", default=None)
        and not config.getoption("deselect", default=None)
        and all(
            os.path.realpath(a) in (
                str(config.rootpath),
                str(config.rootpath / "tests"))
            for a in config.args))
    if missing and unrestricted:
        raise pytest.UsageError(
            f"SMOKE/L1 entries match no collected test: {sorted(missing)}")


@pytest.fixture(autouse=True)
def _fresh_mesh_state():
    yield
    from apex_tpu.parallel import mesh
    mesh.destroy_model_parallel()
