"""Test harness: emulated 8-device CPU mesh.

The reference tests distributed code by spawning ≤4 NCCL processes per
node (apex/transformer/testing/distributed_test_base.py:22-74).  The
TPU-native equivalent runs every test in ONE process against an 8-way
virtual CPU mesh via XLA's host-platform device-count flag — collectives
and shardings compile and execute exactly as on an 8-chip slice.
"""

import os

# Force CPU: the session environment pins JAX_PLATFORMS to the real TPU
# tunnel (axon) and pre-imports jax via sitecustomize, so env vars alone
# are too late — use jax.config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", False)
assert jax.device_count() == 8, jax.devices()

import pytest  # noqa: E402

# Smoke tier (≡ the reference's per-directory L0 subsets,
# tests/L0/run_test.py:19-34): ONE fast, meaningful test per subsystem,
# ~90 s serial on the virtual mesh.  `pytest -m smoke`.  The full suite
# (`pytest tests/`) is the L1 equivalent — ~30 min serial, documented in
# README.  Keep every entry under ~10 s; timings from --durations=0.
SMOKE = {
    # kernels
    "test_flash_attention.py::test_flash_grads[True]",
    "test_softmax.py::test_scaled_masked_softmax",
    "test_layer_norm.py::test_layer_norm_grads[True-shape0]",
    "test_xentropy.py::test_xent_grad[0.0]",
    "test_fused_dense_mlp.py::test_linear_gelu_linear",
    # optimizers
    "test_optimizers.py::test_fused_adam_vs_optax_adamw[0.0]",
    "test_distributed_optimizers.py::test_dist_adam_matches_fused_adam",
    # data parallel / amp
    "test_ddp.py::test_make_train_step_matches_full_batch",
    "test_ddp.py::test_make_train_step_with_amp_dynamic_scaling",
    "test_distributed_tier.py::TestSyncBNDistributed::"
    "test_syncbn_matches_global_bn",
    # model parallel
    "test_tensor_parallel_layers.py::test_column_parallel_linear",
    "test_mesh_collectives.py::test_copy_reduce_pair",
    "test_pipeline_parallel.py::test_pipeline_matches_sequential[4]",
    "test_schedules_common.py::TestSchedulesCommon::"
    "test_backward_step_chain_matches_full_grad",
    # long context
    "test_context_parallel.py::test_ring_attention_matches_dense[False]",
    # models end-to-end
    "test_gpt_minimal.py::test_gpt_trains_tp_dp",
    "test_bert_minimal.py::test_bert_trains_with_lamb",
    # contrib
    "test_contrib_ops.py::test_self_multihead_attn[False]",
    "test_contrib_ops.py::test_transducer_joint",
    "test_contrib_spatial.py::test_spatial_conv_matches_dense",
    "test_misc_components.py::test_rnn_cells[LSTM]",
    # aux subsystems
    "test_checkpoint.py::test_checkpoint_roundtrip",
    "test_host_runtime.py::test_flat_layout",
}


def pytest_collection_modifyitems(config, items):
    matched = set()
    for item in items:
        key = item.nodeid.rsplit("tests/", 1)[-1]
        if key in SMOKE:
            matched.add(key)
            item.add_marker(pytest.mark.smoke)
    missing = SMOKE - matched
    # fail loudly when a rename/reparametrize silently drops a smoke
    # entry — but only when the whole suite was collected (a -k/-m or
    # path-restricted run legitimately sees a subset)
    unrestricted = (
        not config.getoption("keyword", default="")
        and not config.getoption("markexpr", default="")
        and not config.getoption("ignore", default=None)
        and not config.getoption("ignore_glob", default=None)
        and not config.getoption("deselect", default=None)
        and all(
            os.path.realpath(a) in (
                str(config.rootpath),
                str(config.rootpath / "tests"))
            for a in config.args))
    if missing and unrestricted:
        raise pytest.UsageError(
            f"SMOKE entries match no collected test: {sorted(missing)}")


@pytest.fixture(autouse=True)
def _fresh_mesh_state():
    yield
    from apex_tpu.parallel import mesh
    mesh.destroy_model_parallel()
