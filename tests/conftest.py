"""Test harness: emulated 8-device CPU mesh.

The reference tests distributed code by spawning ≤4 NCCL processes per
node (apex/transformer/testing/distributed_test_base.py:22-74).  The
TPU-native equivalent runs every test in ONE process against an 8-way
virtual CPU mesh via XLA's host-platform device-count flag — collectives
and shardings compile and execute exactly as on an 8-chip slice.
"""

import os

# Force CPU: the session environment pins JAX_PLATFORMS to the real TPU
# tunnel (axon) and pre-imports jax via sitecustomize, so env vars alone
# are too late — use jax.config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", False)
assert jax.device_count() == 8, jax.devices()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_mesh_state():
    yield
    from apex_tpu.parallel import mesh
    mesh.destroy_model_parallel()
