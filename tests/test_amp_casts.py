"""Per-op AMP cast/promote matrix ≡ tests/L0/run_amp/test_basic_casts.py
+ test_promotion.py (VERDICT r4 next-#9).

The reference pins its O1 patching engine op by op: allow-list ops
(conv/mm/...) run half, promote-list ops (softmax/norm/loss) run fp32,
and mixed-dtype inputs promote to the widest type.  apex_tpu's AMP is a
policy object applied at call sites, so the same contract is pinned
table-driven against `Policy.compute_for` (the cast-list encoding,
amp/policy.py MATMUL_CLASS_OPS / FP32_CLASS_OPS) and functionally
against the real kernels (internal fp32 for fp32-class ops on bf16
inputs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp.policy import (
    FP32_CLASS_OPS,
    MATMUL_CLASS_OPS,
    get_policy,
)

BF16 = jnp.bfloat16
F32 = jnp.float32

# (opt_level, matmul-class dtype, fp32-class dtype) ≡ the reference
# opt_levels table (frontend.py:104-193): O0 pure fp32; O1/O2 patched /
# master-weight half with fp32 promote list; O3 pure half.
MATRIX = [
    ("O0", F32, F32),
    ("O1", BF16, F32),
    ("O2", BF16, F32),
    ("O3", BF16, BF16),
]


@pytest.mark.parametrize("opt_level,matmul_dt,fp32_dt", MATRIX)
@pytest.mark.parametrize("op", MATMUL_CLASS_OPS)
def test_matmul_class_compute_dtype(opt_level, matmul_dt, fp32_dt, op):
    """≡ test_basic_casts' whitelist loop (conv/mm/matmul run half)."""
    assert get_policy(opt_level).compute_for(op) == matmul_dt


@pytest.mark.parametrize("opt_level,matmul_dt,fp32_dt", MATRIX)
@pytest.mark.parametrize("op", FP32_CLASS_OPS)
def test_fp32_class_compute_dtype(opt_level, matmul_dt, fp32_dt, op):
    """≡ test_basic_casts' fp32-list loop (softmax/norm/loss stay
    fp32 under O1/O2; pure-half under O3)."""
    assert get_policy(opt_level).compute_for(op) == fp32_dt


def test_compound_names_use_fp32_class():
    """Compound op names hit the fp32 list by substring (the reference
    patches functions, which carry their class in the name)."""
    p = get_policy("O1")
    assert p.compute_for("fused_layer_norm") == F32
    assert p.compute_for("masked_softmax") == F32
    assert p.compute_for("fused_dense") == BF16
    assert p.compute_for("flash_attention") == BF16


@pytest.mark.parametrize("opt_level,param_dt,out_dt", [
    ("O0", F32, F32), ("O1", F32, F32), ("O2", BF16, F32),
    ("O3", BF16, BF16),
])
def test_param_and_output_dtypes(opt_level, param_dt, out_dt):
    """≡ cast_model_type / cast_model_outputs rows of the opt_levels
    table (frontend.py:104-193)."""
    p = get_policy(opt_level)
    assert p.param_dtype == param_dt
    assert p.output_dtype == out_dt


def test_promotion_widest_type():
    """≡ test_promotion.py: binary ops on mixed half/fp32 inputs run in
    (promote to) fp32.  Functionally: cast_to_compute leaves dtypes
    uniform, and jnp's own promotion picks fp32 for mixed operands —
    the policy never downcasts an fp32 operand implicitly."""
    a16 = jnp.ones((4, 4), BF16)
    a32 = jnp.ones((4, 4), F32)
    assert (a16 + a32).dtype == F32
    assert jnp.matmul(a16, a32).dtype == F32
    # cast_to_compute under O1 makes everything bf16 (explicit, not
    # implicit) — ints / bools are untouched
    p = get_policy("O1")
    tree = {"w": a32, "mask": jnp.ones((4,), jnp.int32)}
    out = p.cast_to_compute(tree)
    assert out["w"].dtype == BF16
    assert out["mask"].dtype == jnp.int32


# ---------------- functional: real kernels honor the contract --------------


def test_layer_norm_internal_fp32():
    """fp32-class op: bf16 input, bf16 output, fp32-accurate stats —
    the kernel must match the fp32 oracle to bf16 resolution, not to
    bf16-stats resolution."""
    from apex_tpu.ops.layer_norm import fused_layer_norm

    x32 = jax.random.normal(jax.random.PRNGKey(0), (64, 256)) * 100.0
    x16 = x32.astype(BF16)
    g = jnp.ones((256,))
    b = jnp.zeros((256,))
    y16 = fused_layer_norm(x16, g, b)
    assert y16.dtype == BF16
    y_oracle = fused_layer_norm(x32, g, b)
    np.testing.assert_allclose(np.asarray(y16, np.float32),
                               np.asarray(y_oracle), rtol=2e-2,
                               atol=2e-2)


def test_softmax_internal_fp32():
    from apex_tpu.transformer.functional.fused_softmax import (
        FusedScaleMaskSoftmax,
    )

    sm = FusedScaleMaskSoftmax()
    x = (jax.random.normal(jax.random.PRNGKey(1), (2, 2, 32, 32))
         * 30.0).astype(BF16)
    y = sm(x)
    assert y.dtype == BF16
    s = np.asarray(jnp.sum(y.astype(F32), axis=-1))
    np.testing.assert_allclose(s, np.ones_like(s), rtol=2e-2, atol=2e-2)


def test_xentropy_loss_fp32():
    """Loss-class op returns fp32 regardless of logits dtype."""
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    logits = jax.random.normal(jax.random.PRNGKey(2), (8, 128)).astype(
        BF16)
    labels = jax.random.randint(jax.random.PRNGKey(3), (8,), 0, 128)
    loss = softmax_cross_entropy_loss(logits, labels)
    assert loss.dtype == F32


def test_batch_stats_fp32():
    """norm-class statistics accumulate fp32 on bf16 activations."""
    from apex_tpu.ops import welford

    x = (jax.random.normal(jax.random.PRNGKey(4), (32, 8, 8, 16))
         + 10.0).astype(BF16)
    mean, var, count = welford.batch_stats(x, (0, 1, 2))
    assert mean.dtype == F32 and var.dtype == F32
    np.testing.assert_allclose(
        np.asarray(mean), np.asarray(x.astype(F32)).mean((0, 1, 2)),
        rtol=1e-2, atol=1e-2)
    assert np.all(np.asarray(var) >= 0)  # sumsq-mean² in bf16 would go
    # negative at mean>>std


def test_matmul_class_runs_bf16_under_o1():
    """The O1-cast train path really computes matmul-class ops in bf16:
    params cast to compute dtype → dense output is bf16."""
    from apex_tpu.ops.fused_dense import linear_bias

    p = get_policy("O1")
    w = jax.random.normal(jax.random.PRNGKey(5), (32, 16))
    b = jnp.zeros((16,))
    x = jax.random.normal(jax.random.PRNGKey(6), (4, 32))
    wc, bc, xc = p.cast_to_compute((w, b, x))
    assert wc.dtype == BF16
    y = linear_bias(xc, wc, bc)
    assert y.dtype == BF16


def test_o2_master_weights_roundtrip():
    """O2 keeps fp32 masters next to bf16 model params
    (≡ _initialize.py:178-203 + fp16_utils master flow)."""
    from apex_tpu.amp.policy import (
        master_params_to_model_params,
        prep_param_lists,
    )

    params = {"w": jax.random.normal(jax.random.PRNGKey(7), (8, 8),
                                     dtype=F32).astype(BF16)}
    model_p, master = prep_param_lists(params)
    assert master["w"].dtype == F32
    updated = jax.tree.map(lambda m: m + 0.5, master)
    back = master_params_to_model_params(updated, model_p)
    assert back["w"].dtype == BF16
