"""Fused softmax family parity ≡ tests/L0/run_transformer fused softmax
tests — Pallas (interpret on CPU) vs jnp reference, fwd + bwd."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.softmax import (
    scaled_masked_softmax,
    scaled_masked_softmax_reference,
    scaled_softmax,
    scaled_softmax_reference,
    scaled_upper_triang_masked_softmax,
    scaled_upper_triang_masked_softmax_reference,
)


@pytest.mark.parametrize("shape", [(2, 4, 8, 16), (1, 2, 5, 7)])
@pytest.mark.parametrize("scale", [1.0, 0.125])
def test_scaled_softmax(shape, scale):
    x = jax.random.normal(jax.random.PRNGKey(0), shape)
    got = scaled_softmax(x, scale, use_pallas_override=True)
    want = scaled_softmax_reference(x, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)

    g1 = jax.grad(lambda a: jnp.sum(
        jnp.tanh(scaled_softmax(a, scale, use_pallas_override=True))))(x)
    g2 = jax.grad(lambda a: jnp.sum(
        jnp.tanh(scaled_softmax_reference(a, scale))))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


def test_scaled_masked_softmax():
    shape = (2, 4, 8, 16)
    x = jax.random.normal(jax.random.PRNGKey(1), shape)
    mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.3,
                                (2, 1, 8, 16))
    got = scaled_masked_softmax(x, mask, 0.5, use_pallas_override=True)
    want = scaled_masked_softmax_reference(x, mask, 0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)

    g1 = jax.grad(lambda a: jnp.sum(jnp.sin(
        scaled_masked_softmax(a, mask, 0.5, use_pallas_override=True))))(x)
    g2 = jax.grad(lambda a: jnp.sum(jnp.sin(
        scaled_masked_softmax_reference(a, mask, 0.5))))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


def test_fully_masked_row_uniform():
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 2, 8))
    mask = jnp.ones((1, 1, 2, 8), bool)
    got = scaled_masked_softmax(x, mask, 1.0, use_pallas_override=True)
    np.testing.assert_allclose(np.asarray(got), 1.0 / 8, rtol=1e-5)


@pytest.mark.parametrize("sq", [8, 13])
def test_causal_softmax(sq):
    x = jax.random.normal(jax.random.PRNGKey(4), (3, sq, sq))
    got = scaled_upper_triang_masked_softmax(x, 0.3,
                                             use_pallas_override=True)
    want = scaled_upper_triang_masked_softmax_reference(x, 0.3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    # strictly-upper entries ~ 0 (reference: -10000 logits)
    upper = np.triu(np.ones((sq, sq), bool), k=1)
    assert np.asarray(got)[:, upper].max() < 1e-4

    g1 = jax.grad(lambda a: jnp.sum(jnp.cos(
        scaled_upper_triang_masked_softmax(a, 0.3, use_pallas_override=True))))(x)
    g2 = jax.grad(lambda a: jnp.sum(jnp.cos(
        scaled_upper_triang_masked_softmax_reference(a, 0.3))))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


def test_bf16():
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 2, 8, 32), jnp.bfloat16)
    got = scaled_softmax(x, 1.0, use_pallas_override=True)
    want = scaled_softmax_reference(x, 1.0)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)
