"""ZeRO-2 optimizer tests ≡ apex/contrib/test/optimizers/test_dist_adam.py:
DistributedFusedAdam over dp=8 must match single-rank FusedAdam exactly
(same updates, 1/8 the state per rank); DistributedFusedLAMB smoke."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    DistributedFusedAdamState,
    DistributedFusedLAMB,
    DistributedFusedLAMBState,
)
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.optimizers.fused_lamb import FusedLAMB
from apex_tpu.parallel import mesh as M

DP = 8


def _params(key):
    k1, k2 = jax.random.split(key)
    return {"w": jax.random.normal(k1, (13, 7)),
            "b": jax.random.normal(k2, (7,))}


def test_dist_adam_matches_fused_adam():
    mesh = M.initialize_model_parallel()  # dp=8
    params = _params(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(num_shards=DP, lr=1e-2, weight_decay=0.01,
                               use_pallas=False)

    # per-rank grads: rank r gets base + r; psum_scatter averages → the
    # reference update uses mean over dp
    base = _params(jax.random.PRNGKey(1))

    def local_init(p):
        return opt.init(p)

    def local_step(state, p_base):
        rank = jax.lax.axis_index("dp").astype(jnp.float32)
        grads = jax.tree_util.tree_map(
            lambda g: g * (1.0 + 0.1 * rank), p_base)
        return opt.step(state, grads)

    sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(
        local_init, mesh=mesh, in_specs=(P(),), out_specs=sspec,
        check_vma=False))(params)

    step = jax.jit(shard_map(
        local_step, mesh=mesh, in_specs=(sspec, P()),
        out_specs=(P(), sspec), check_vma=False))

    new_params, state = step(state, base)

    # reference: plain FusedAdam with the MEAN grad over ranks
    ref = FusedAdam(lr=1e-2, weight_decay=0.01, use_pallas=False)
    rstate = ref.init(params)
    mean_scale = np.mean([1.0 + 0.1 * r for r in range(DP)])
    mean_grads = jax.tree_util.tree_map(lambda g: g * mean_scale, base)
    ref_params, rstate = ref.step(rstate, mean_grads)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        new_params, ref_params)

    # state really is sharded: each rank holds padded_total/8 elements,
    # where padding rounds to num_shards x FLAT_TILE so every shard is a
    # whole Pallas tile (in-place kernel, no per-step pad copies)
    from apex_tpu.ops.optimizer_kernels import FLAT_TILE
    total = 13 * 7 + 7
    unit = DP * FLAT_TILE
    padded = total + (-total) % unit
    assert state.exp_avg.shape == (padded,)  # global view = 8 x shard


def test_dist_lamb_smoke_and_parity():
    mesh = M.initialize_model_parallel()
    params = _params(jax.random.PRNGKey(2))
    grads = _params(jax.random.PRNGKey(3))
    opt = DistributedFusedLAMB(num_shards=DP, lr=1e-2, weight_decay=0.0,
                               max_grad_norm=1e9, use_pallas=False)

    def local_init(p):
        return opt.init(p)

    def local_step(state, g):
        return opt.step(state, g)

    sspec = DistributedFusedLAMBState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(local_init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    step = jax.jit(shard_map(local_step, mesh=mesh, in_specs=(sspec, P()),
                             out_specs=(P(), sspec), check_vma=False))
    new_params, state = step(state, grads)

    # parity vs single-rank FusedLAMB with identical grads (each rank
    # contributed the same grads → psum_scatter/num_shards == grads)
    ref = FusedLAMB(lr=1e-2, weight_decay=0.0, max_grad_norm=1e9,
                    use_pallas=False)
    rstate = ref.init(params)
    ref_params, _ = ref.step(rstate, grads)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        new_params, ref_params)


def test_zero_optimizer_layout_guard():
    """ZeRO state_dicts carry the flat-layout fingerprint; restoring a
    pre-layout (or mismatched) checkpoint fails loudly instead of
    scrambling the lane-aligned offsets."""
    mesh = M.initialize_model_parallel()
    params = {"w": jnp.ones((300,)), "b": jnp.ones((7,))}
    opt = DistributedFusedLAMB(num_shards=DP, lr=1e-3)
    sspec = DistributedFusedLAMBState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(
        lambda p: opt.init(p), mesh=mesh, in_specs=(P(),),
        out_specs=sspec, check_vma=False))(params)
    d = opt.state_dict(state)
    assert d["flat_layout"]["align"] == 128
    restored = opt.load_state_dict(d)
    assert restored.params_shard.shape == state.params_shard.shape
    bad = {k: v for k, v in d.items() if k != "flat_layout"}
    with pytest.raises(ValueError, match="flat_layout"):
        opt.load_state_dict(bad)
    M.destroy_model_parallel()
