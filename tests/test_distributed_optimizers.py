"""ZeRO-2 optimizer tests ≡ apex/contrib/test/optimizers/test_dist_adam.py:
DistributedFusedAdam over dp=8 must match single-rank FusedAdam exactly
(same updates, 1/8 the state per rank); DistributedFusedLAMB smoke."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    DistributedFusedAdamState,
    DistributedFusedLAMB,
    DistributedFusedLAMBState,
)
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.optimizers.fused_lamb import FusedLAMB
from apex_tpu.parallel import mesh as M

DP = 8


def _params(key):
    k1, k2 = jax.random.split(key)
    return {"w": jax.random.normal(k1, (13, 7)),
            "b": jax.random.normal(k2, (7,))}


def test_dist_adam_matches_fused_adam():
    mesh = M.initialize_model_parallel()  # dp=8
    params = _params(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(num_shards=DP, lr=1e-2, weight_decay=0.01,
                               use_pallas=False)

    # per-rank grads: rank r gets base + r; psum_scatter averages → the
    # reference update uses mean over dp
    base = _params(jax.random.PRNGKey(1))

    def local_init(p):
        return opt.init(p)

    def local_step(state, p_base):
        rank = jax.lax.axis_index("dp").astype(jnp.float32)
        grads = jax.tree_util.tree_map(
            lambda g: g * (1.0 + 0.1 * rank), p_base)
        return opt.step(state, grads)

    sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(
        local_init, mesh=mesh, in_specs=(P(),), out_specs=sspec,
        check_vma=False))(params)

    step = jax.jit(shard_map(
        local_step, mesh=mesh, in_specs=(sspec, P()),
        out_specs=(P(), sspec), check_vma=False))

    new_params, state = step(state, base)

    # reference: plain FusedAdam with the MEAN grad over ranks
    ref = FusedAdam(lr=1e-2, weight_decay=0.01, use_pallas=False)
    rstate = ref.init(params)
    mean_scale = np.mean([1.0 + 0.1 * r for r in range(DP)])
    mean_grads = jax.tree_util.tree_map(lambda g: g * mean_scale, base)
    ref_params, rstate = ref.step(rstate, mean_grads)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        new_params, ref_params)

    # state really is sharded: each rank holds padded_total/8 elements,
    # where padding rounds to num_shards x FLAT_TILE so every shard is a
    # whole Pallas tile (in-place kernel, no per-step pad copies)
    from apex_tpu.ops.optimizer_kernels import FLAT_TILE
    total = 13 * 7 + 7
    unit = DP * FLAT_TILE
    padded = total + (-total) % unit
    assert state.exp_avg.shape == (padded,)  # global view = 8 x shard


def test_dist_lamb_smoke_and_parity():
    mesh = M.initialize_model_parallel()
    params = _params(jax.random.PRNGKey(2))
    grads = _params(jax.random.PRNGKey(3))
    opt = DistributedFusedLAMB(num_shards=DP, lr=1e-2, weight_decay=0.0,
                               max_grad_norm=1e9, use_pallas=False)

    def local_init(p):
        return opt.init(p)

    def local_step(state, g):
        return opt.step(state, g)

    sspec = DistributedFusedLAMBState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(local_init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    step = jax.jit(shard_map(local_step, mesh=mesh, in_specs=(sspec, P()),
                             out_specs=(P(), sspec), check_vma=False))
    new_params, state = step(state, grads)

    # parity vs single-rank FusedLAMB with identical grads (each rank
    # contributed the same grads → psum_scatter/num_shards == grads)
    ref = FusedLAMB(lr=1e-2, weight_decay=0.0, max_grad_norm=1e9,
                    use_pallas=False)
    rstate = ref.init(params)
    ref_params, _ = ref.step(rstate, grads)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        new_params, ref_params)


def test_zero_optimizer_layout_guard():
    """ZeRO state_dicts carry the flat-layout fingerprint; restoring a
    pre-layout (or mismatched) checkpoint fails loudly instead of
    scrambling the lane-aligned offsets."""
    mesh = M.initialize_model_parallel()
    params = {"w": jnp.ones((300,)), "b": jnp.ones((7,))}
    opt = DistributedFusedLAMB(num_shards=DP, lr=1e-3)
    sspec = DistributedFusedLAMBState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(
        lambda p: opt.init(p), mesh=mesh, in_specs=(P(),),
        out_specs=sspec, check_vma=False))(params)
    d = opt.state_dict(state)
    assert d["flat_layout"]["align"] == 128
    restored = opt.load_state_dict(d)
    assert restored.params_shard.shape == state.params_shard.shape
    bad = {k: v for k, v in d.items() if k != "flat_layout"}
    with pytest.raises(ValueError, match="flat_layout"):
        opt.load_state_dict(bad)
    M.destroy_model_parallel()


# --------------------- scale / invariance (round 2, VERDICT #7) -------------

def _big_params(total_m=100):
    """~total_m million params in a few transformer-shaped leaves."""
    n = int(total_m * 1e6)
    side = 4096
    big = n // (2 * side)
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 3)
    return {
        "wq": jax.random.normal(ks[0], (side, big)) * 0.02,
        "wk": jax.random.normal(ks[1], (big, side)) * 0.02,
        "ln": jax.random.normal(ks[2], (side,)),
    }


def _zero_steps(opt_cls, params, grads, num_shards, steps=2, **kw):
    mesh = M.initialize_model_parallel(
        devices=jax.devices()[:num_shards])
    opt = opt_cls(num_shards=num_shards, lr=1e-2, use_pallas=False, **kw)
    sspec = opt._STATE(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)

    def local_step(state, g):
        return opt.step(state, g)

    step = jax.jit(shard_map(local_step, mesh=mesh, in_specs=(sspec, P()),
                             out_specs=(P(), sspec), check_vma=False))
    full = None
    for _ in range(steps):
        full, state = step(state, grads)
    M.destroy_model_parallel()
    return full, state, opt


def test_dist_adam_100m_scale_and_state_roundtrip():
    """dp=8 DistributedFusedAdam at 100M params on the virtual mesh:
    per-rank state is 1/8 of the padded total, updates match unsharded
    FusedAdam, and the sharded state_dict round-trips (≡ the reference's
    test_dist_adam.py scale + state gather/scatter paths)."""
    M.destroy_model_parallel()
    params = _big_params(100)
    grads = jax.tree_util.tree_map(lambda p: p * 0.01, params)
    full, state, opt = _zero_steps(DistributedFusedAdam, params, grads, DP)

    total = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(params))
    assert total >= 100_000_000
    # the state really is dp-sharded: each device holds exactly 1/DP of
    # the padded buffer (inspect the device-local shards, not the
    # logically-gathered global view)
    padded = state.params_shard.shape[0]
    assert padded >= total and padded % DP == 0
    for buf in (state.params_shard, state.exp_avg, state.exp_avg_sq):
        shards = buf.addressable_shards
        assert len(shards) == DP
        assert all(sh.data.shape[0] == padded // DP for sh in shards)

    ref = FusedAdam(lr=1e-2, use_pallas=False)
    rstate = ref.init(params)
    rp = params
    for _ in range(2):
        rp, rstate = ref.step(rstate, grads)
    np.testing.assert_allclose(np.asarray(full["wq"][:2, :64]),
                               np.asarray(rp["wq"][:2, :64]),
                               rtol=1e-5, atol=1e-6)

    # state_dict round trip at scale: resumed state continues identically
    d = opt.state_dict(state)
    restored = opt.load_state_dict(
        {k: np.asarray(v) if hasattr(v, "shape") else v
         for k, v in d.items()})
    for a, b in zip(state, restored):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dist_adam_shard_count_invariance():
    """The same optimization trajectory regardless of dp shard count
    (4 vs 8 ranks) — resulting full params must agree."""
    M.destroy_model_parallel()
    params = _params(jax.random.PRNGKey(2))
    grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
    full8, _, _ = _zero_steps(DistributedFusedAdam, params, grads, 8,
                              steps=3)
    full4, _, _ = _zero_steps(DistributedFusedAdam, params, grads, 4,
                              steps=3)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        full8, full4)


def test_dist_lamb_100m_scale():
    """dp=8 LAMB at 100M params: updates must MATCH unsharded FusedLAMB
    (not just stay finite) — the shard-local per-tensor norm path has to
    reproduce the full-buffer trust ratios exactly."""
    M.destroy_model_parallel()
    params = _big_params(100)
    grads = jax.tree_util.tree_map(lambda p: p * 0.01, params)
    full, state, opt = _zero_steps(DistributedFusedLAMB, params, grads, DP,
                                   steps=1, weight_decay=0.0,
                                   max_grad_norm=1e9)
    assert state.params_shard.shape[0] % DP == 0

    ref = FusedLAMB(lr=1e-2, weight_decay=0.0, max_grad_norm=1e9,
                    use_pallas=False)
    rstate = ref.init(params)
    rp, _ = ref.step(rstate, grads)
    np.testing.assert_allclose(np.asarray(full["wq"][:2, :64]),
                               np.asarray(rp["wq"][:2, :64]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(full["ln"]),
                               np.asarray(rp["ln"]),
                               rtol=1e-4, atol=1e-6)


def test_dist_lamb_shard_count_invariance():
    """Identical trajectories at dp=4 vs dp=8 — per-tensor norms must
    not depend on how the flat buffer is sharded."""
    M.destroy_model_parallel()
    params = _params(jax.random.PRNGKey(5))
    grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
    full8, _, _ = _zero_steps(DistributedFusedLAMB, params, grads, 8,
                              steps=3, weight_decay=0.01)
    full4, _, _ = _zero_steps(DistributedFusedLAMB, params, grads, 4,
                              steps=3, weight_decay=0.01)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        full8, full4)


def test_dist_adam_grad_and_param_sync_dtypes():
    """bf16 grad reduce-scatter + bf16 param all-gather (≡ the
    reference's grad_sync_dtype/param_sync_dtype options,
    test_dist_adam.py dtype sweeps): training stays close to the fp32
    sync within bf16 tolerance, and the AUTHORED step contains NO
    fp32 all-gather when params are bf16 (monitor.comms inventory —
    ISSUE 7 port of the hand-rolled stablehlo regex).  The inventory
    runs `optimized=False` (pre-optimization HLO): CPU XLA's
    float-normalization pass rewrites every bf16 collective to f32 in
    the OPTIMIZED module (a backend lowering artifact — on TPU it
    stays bf16), so the authored wire dtype is only visible pre-opt
    here."""
    from apex_tpu.monitor import comms
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel()
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), _params(jax.random.PRNGKey(0)))
    grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)

    def run(**kw):
        opt = DistributedFusedAdam(num_shards=DP, lr=1e-2,
                                   use_pallas=False, **kw)
        sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
        state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                                  out_specs=sspec, check_vma=False))(params)
        step = jax.jit(shard_map(lambda s, g: opt.step(s, g), mesh=mesh,
                                 in_specs=(sspec, P()),
                                 out_specs=(P(), sspec), check_vma=False))
        full, _ = step(state, grads)
        return full, comms.comms_report(step, (state, grads), mesh=mesh,
                                        optimized=False)

    full_bf16, rep = run(grad_sync_dtype=jnp.bfloat16)
    full_fp32, _ = run(grad_sync_dtype=jnp.float32,
                       param_sync_dtype=jnp.float32)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-2, atol=1e-3),
        full_bf16, full_fp32)
    # param gather followed leaf dtype (bf16): no f32 all-gather ops
    ags = [c for c in rep.collectives
           if c.kind == "all-gather" and c.dtype == "f32"]
    assert not ags, f"fp32 all-gather found: {ags[:1]}"
    # the gathers that DO exist ride the dp axis in bf16
    bf = [c for c in rep.collectives if c.kind == "all-gather"]
    assert bf and all(c.dtype == "bf16" and c.axes == ("dp",)
                      for c in bf), bf
    M.destroy_model_parallel()


def test_dist_lamb_single_full_size_allgather_hlo():
    """HLO probe (VERDICT r2 #3): the ONLY all-gather in a
    DistributedFusedLAMB step is the final param sync — the per-tensor
    norm pass must not gather the params or the update buffer.
    Counted by the monitor.comms inventory on the OPTIMIZED module
    (ISSUE 7 port of the hand-rolled op-count regex), which also pins
    the gather's axis and shard size — claims the regex couldn't
    make."""
    from apex_tpu.monitor import comms
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel()
    params = _params(jax.random.PRNGKey(6))
    grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
    opt = DistributedFusedLAMB(num_shards=DP, lr=1e-2, use_pallas=False)
    sspec = DistributedFusedLAMBState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    step = jax.jit(shard_map(lambda s, g: opt.step(s, g), mesh=mesh,
                             in_specs=(sspec, P()),
                             out_specs=(P(), sspec), check_vma=False))
    rep = comms.comms_report(step, (state, grads), mesh=mesh)
    ags = [c for c in rep.collectives if c.kind == "all-gather"]
    assert len(ags) == 1, \
        f"expected exactly 1 all-gather (param sync), got {ags}"
    (ag,) = ags
    assert ag.axes == ("dp",) and ag.group_size == DP
    # operand = this rank's padded shard of the flat param buffer
    assert ag.operand_bytes == state.params_shard.shape[0] // DP * 4
    M.destroy_model_parallel()


# --------- bucketed backward-overlap grad sync (round 4: VERDICT #4) --------

def _gpt_like_params(key, n_layers=6, h=64):
    ks = jax.random.split(key, n_layers)
    return {f"block{i}": {"w1": jax.random.normal(k, (h, 4 * h)) * 0.02,
                          "w2": jax.random.normal(k, (4 * h, h)) * 0.02,
                          "b": jnp.zeros((h,))}
            for i, k in enumerate(ks)}


def test_dist_adam_bucketed_matches_single_bucket():
    """n_buckets=4 (bucket-major shard layout, 4 reduce-scatters) must
    produce bit-identical FULL params to the single-bucket step."""
    mesh = M.initialize_model_parallel()
    params = _gpt_like_params(jax.random.PRNGKey(0))
    base = _gpt_like_params(jax.random.PRNGKey(1))

    def run(n_buckets, steps=3):
        opt = DistributedFusedAdam(num_shards=DP, lr=1e-2,
                                   weight_decay=0.01,
                                   n_buckets=n_buckets, use_pallas=False)
        sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
        state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                                  out_specs=sspec, check_vma=False))(params)

        def local_step(state, g):
            rank = jax.lax.axis_index("dp").astype(jnp.float32)
            grads = jax.tree_util.tree_map(
                lambda x: x * (1.0 + 0.1 * rank), g)
            return opt.step(state, grads)

        step = jax.jit(shard_map(local_step, mesh=mesh,
                                 in_specs=(sspec, P()),
                                 out_specs=(P(), sspec),
                                 check_vma=False))
        p = None
        for _ in range(steps):
            p, state = step(state, base)
        return p

    p1 = run(1)
    p4 = run(4)
    flat1 = jax.tree_util.tree_leaves(p1)
    flat4 = jax.tree_util.tree_leaves(p4)
    for a, b in zip(flat1, flat4):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dist_adam_bucketed_reduce_scatters_interleavable():
    """The lowered train step must contain >= n_buckets reduce-scatters
    whose operands are per-bucket (NOT one fused buffer), with the
    first reduce-scatter appearing before the last backward matmul —
    i.e. the schedule is free to overlap grad sync with backward
    (≡ the reference's per-bucket grad hooks)."""
    mesh = M.initialize_model_parallel()
    params = _gpt_like_params(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(num_shards=DP, lr=1e-2, n_buckets=4,
                               use_pallas=False)
    sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    x = jax.random.normal(jax.random.PRNGKey(2), (16, 64))

    def local_step(state, x):
        full = opt.full_params(state)

        def loss(p):
            h = x
            for i in range(6):
                blk = p[f"block{i}"]
                h = h + jnp.tanh(h @ blk["w1"]) @ blk["w2"] + blk["b"]
            return jnp.mean(h ** 2)

        grads = jax.grad(loss)(full)
        return opt.step(state, grads)

    step = jax.jit(shard_map(local_step, mesh=mesh, in_specs=(sspec, P()),
                             out_specs=(P(), sspec), check_vma=False))
    # ISSUE 7: the old probe compared TEXTUAL positions of the last
    # "dot(" vs the first "reduce-scatter(" in the HLO dump — print
    # order, not schedule order, and it flaked across XLA versions
    # (ADVICE r4).  The monitor.comms analyzer replaces it with the
    # real classification: per-bucket inventory on the optimized
    # module, and — where the backend emits async start/done pairs —
    # the dot flops actually scheduled inside each collective's window.
    from apex_tpu.monitor import comms
    rep = comms.comms_report(step, (state, x), mesh=mesh)
    rs = [c for c in rep.collectives if c.kind == "reduce-scatter"]
    assert len(rs) >= 4, \
        f"expected >=4 per-bucket reduce-scatters, got {len(rs)}"
    # per-bucket operands (NOT one fused buffer): every reduce-scatter
    # moves a strict subset of the full padded flat buffer, over dp
    full_bytes = state.exp_avg.shape[0] * 4
    assert all(c.axes == ("dp",) and 0 < c.operand_bytes < full_bytes
               for c in rs), rs
    # the schedule-order property, measured instead of grepped: on a
    # backend with async collectives a serialized bucket is a finding;
    # CPU emits sync collectives only, and the analyzer must say the
    # plane is unmeasurable rather than fake a verdict
    if rep.async_supported:
        ser = [c for c in rs if c.serialized]
        assert not ser, f"serialized per-bucket reduce-scatters: {ser}"
    else:
        assert all(c.overlap_fraction is None for c in rs)
        assert rep.overlap_ok  # vacuous, never a fake verdict


def test_dist_adam_bf16_master_state():
    """ZeRO-2 with bf16 master state: shard dtype is bf16 (half the
    per-rank state memory) and updates track the fp32-state run."""
    mesh = M.initialize_model_parallel()
    params = _params(jax.random.PRNGKey(0))
    base = _params(jax.random.PRNGKey(1))

    def run(dt):
        opt = DistributedFusedAdam(num_shards=DP, lr=1e-2,
                                   master_dtype=dt, use_pallas=False)
        sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
        state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                                  out_specs=sspec, check_vma=False))(params)

        def local_step(state, g):
            return opt.step(state, g)

        step = jax.jit(shard_map(local_step, mesh=mesh,
                                 in_specs=(sspec, P()),
                                 out_specs=(P(), sspec), check_vma=False))
        p = None
        for _ in range(3):
            p, state = step(state, base)
        return p, state

    p32, _ = run(jnp.float32)
    p16, st16 = run(jnp.bfloat16)
    assert st16.params_shard.dtype == jnp.bfloat16
    assert st16.exp_avg.dtype == jnp.bfloat16
    for a, e in zip(jax.tree_util.tree_leaves(p16),
                    jax.tree_util.tree_leaves(p32)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(e, np.float32),
                                   rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("n_buckets", [1, 4])
def test_dist_adam_wd_mask_matches_fused_adam(n_buckets):
    """ZeRO-2 per-leaf hyperparameters: every rank applies the right
    per-tensor wd/lr inside its bucket shard (global row offsets) —
    must match single-rank FusedAdam with the same mask."""
    mesh = M.initialize_model_parallel()
    params = _gpt_like_params(jax.random.PRNGKey(0))
    mask = jax.tree_util.tree_map_with_path(
        lambda path, l: "b" not in str(path[-1]), params)
    scales = jax.tree_util.tree_map_with_path(
        lambda path, l: 0.5 if "w2" in str(path[-1]) else 1.0, params)
    opt = DistributedFusedAdam(num_shards=DP, lr=1e-2, weight_decay=0.1,
                               n_buckets=n_buckets, wd_mask=mask,
                               lr_scales=scales, use_pallas=False)
    base = _gpt_like_params(jax.random.PRNGKey(1))

    sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)

    def local_step(state, g):
        rank = jax.lax.axis_index("dp").astype(jnp.float32)
        grads = jax.tree_util.tree_map(
            lambda x: x * (1.0 + 0.1 * rank), g)
        return opt.step(state, grads)

    step = jax.jit(shard_map(local_step, mesh=mesh,
                             in_specs=(sspec, P()),
                             out_specs=(P(), sspec), check_vma=False))
    new_params, state = step(state, base)

    ref = FusedAdam(lr=1e-2, weight_decay=0.1, wd_mask=mask,
                    lr_scales=scales, use_pallas=False)
    rstate = ref.init(params)
    mean_scale = np.mean([1.0 + 0.1 * r for r in range(DP)])
    mean_grads = jax.tree_util.tree_map(lambda g: g * mean_scale, base)
    ref_params, rstate = ref.step(rstate, mean_grads)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        new_params, ref_params)


def test_dist_lamb_wd_mask_matches_fused_lamb():
    """Sharded LAMB with a no-decay mask matches single-rank FusedLAMB
    (shard row offsets feed the phase-1 segment expansion)."""
    mesh = M.initialize_model_parallel()
    params = _params(jax.random.PRNGKey(4))
    mask = {"w": True, "b": False}
    scales = {"w": 1.0, "b": 0.5}
    base = _params(jax.random.PRNGKey(5))

    opt = DistributedFusedLAMB(num_shards=DP, lr=1e-2, weight_decay=0.1,
                               wd_mask=mask, lr_scales=scales,
                               use_pallas=False)
    sspec = DistributedFusedLAMBState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)

    def local_step(state, g):
        return opt.step(state, g)

    step = jax.jit(shard_map(local_step, mesh=mesh,
                             in_specs=(sspec, P()),
                             out_specs=(P(), sspec), check_vma=False))
    new_params, state = step(state, base)

    ref = FusedLAMB(lr=1e-2, weight_decay=0.1, wd_mask=mask,
                    lr_scales=scales, use_pallas=False)
    rstate = ref.init(params)
    ref_params, rstate = ref.step(rstate, base)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        new_params, ref_params)


# -------- ZeRO-2 reshardable checkpoints + param-sync overlap (r5) ----------


def test_dist_adam_gathered_checkpoint_reshards():
    """State written at (dp=8, n_buckets=4) restores at (dp=4,
    n_buckets=1) — and continues bit-identically (VERDICT r4 next-#5)."""
    M.destroy_model_parallel()
    params = _gpt_like_params(jax.random.PRNGKey(0))
    base = _gpt_like_params(jax.random.PRNGKey(1))

    def build(num_shards, n_buckets):
        mesh = M.initialize_model_parallel(
            devices=jax.devices()[:num_shards])
        opt = DistributedFusedAdam(num_shards=num_shards, lr=1e-2,
                                   weight_decay=0.01,
                                   n_buckets=n_buckets, use_pallas=False)
        sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
        state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                                  out_specs=sspec,
                                  check_vma=False))(params)
        step = jax.jit(shard_map(
            lambda s, g: opt.step(s, g), mesh=mesh,
            in_specs=(sspec, P()), out_specs=(P(), sspec),
            check_vma=False))
        return mesh, opt, sspec, state, step

    # run 2 steps at dp=8 x b4, save gathered
    mesh, opt8, sspec, state, step = build(8, 4)
    for _ in range(2):
        full_a, state = step(state, base)
    gathered = jax.jit(shard_map(
        opt8.gather_state_dict, mesh=mesh, in_specs=(sspec,),
        out_specs=P(), check_vma=False))(state)
    assert "params" in gathered and "params_shard" not in gathered
    # every gathered leaf is model-shaped
    jax.tree_util.tree_map(lambda g, p: None
                           if g.shape == p.shape else 1 / 0,
                           gathered["params"], params)
    # to host, as a real save/load would (devices change across meshes)
    gathered = jax.tree_util.tree_map(np.asarray, gathered)
    M.destroy_model_parallel()

    # restore at dp=4 x b1 and continue
    mesh4, opt4, sspec4, state4, step4 = build(4, 1)
    state4 = jax.jit(shard_map(
        opt4.load_gathered_state_dict, mesh=mesh4, in_specs=(P(),),
        out_specs=sspec4, check_vma=False))(gathered)
    full_b, state4 = step4(state4, base)
    M.destroy_model_parallel()

    # reference continuation at dp=8 x b4
    mesh, opt8b, sspec, state8, step8 = build(8, 4)
    state8 = jax.jit(shard_map(
        opt8b.load_gathered_state_dict, mesh=mesh, in_specs=(P(),),
        out_specs=sspec, check_vma=False))(gathered)
    full_a2, state8 = step8(state8, base)
    M.destroy_model_parallel()

    # dp=8 and dp=4 reduce-scatters sum in different tree orders, so
    # the continuations agree to float addition-order tolerance, not
    # bitwise
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-7),
        full_a2, full_b)


def test_dist_adam_gather_deferred():
    """gather_params=False returns (None, state); full_params(state)
    reconstructs exactly what the gathering step would have returned."""
    mesh = M.initialize_model_parallel()
    params = _params(jax.random.PRNGKey(6))
    base = _params(jax.random.PRNGKey(7))
    opt = DistributedFusedAdam(num_shards=DP, lr=1e-2, use_pallas=False)
    sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
    state0 = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                               out_specs=sspec, check_vma=False))(params)

    def step_gather(s, g):
        return opt.step(s, g)

    def step_defer(s, g):
        none_, s2 = opt.step(s, g, gather_params=False)
        return opt.full_params(s2), s2

    f1 = jax.jit(shard_map(step_gather, mesh=mesh, in_specs=(sspec, P()),
                           out_specs=(P(), sspec), check_vma=False))
    f2 = jax.jit(shard_map(step_defer, mesh=mesh, in_specs=(sspec, P()),
                           out_specs=(P(), sspec), check_vma=False))
    p1, _ = f1(state0, base)
    p2, _ = f2(state0, base)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-6, atol=1e-7), p1, p2)


def test_dist_adam_bucketed_param_gathers_interleavable():
    """The per-bucket step must emit >= n_buckets SEPARATE param
    all-gathers (one per bucket's adam output) — the structural
    precondition for overlapping bucket k's gather with bucket k+1's
    update (≡ the reference's side-stream bucket pipeline)."""
    mesh = M.initialize_model_parallel()
    params = _gpt_like_params(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(num_shards=DP, lr=1e-2, n_buckets=4,
                               use_pallas=False)
    sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    base = _gpt_like_params(jax.random.PRNGKey(1))
    step = jax.jit(shard_map(lambda s, g: opt.step(s, g), mesh=mesh,
                             in_specs=(sspec, P()),
                             out_specs=(P(), sspec), check_vma=False))
    hlo = step.lower(state, base).compile().as_text()
    n_ag = hlo.count("all-gather(")
    assert n_ag >= 4, f"expected >=4 per-bucket all-gathers, got {n_ag}"
