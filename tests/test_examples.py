"""Smoke tests for the example scripts (≡ the reference's examples/
being exercised by tests/L1 shell drivers, tests/L1/common/run_test.sh).

Each example must run end-to-end on the CPU test mesh and report a
finite loss — the L1 tier's "does the intended workflow actually run"
check, scaled down to CI size.
"""

import os
import pathlib
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run(script, *args, timeout=600):
    return subprocess.run(
        [sys.executable, str(ROOT / "examples" / script), *args],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_dcgan_runs(opt_level):
    # --force-cpu-devices: JAX_PLATFORMS=cpu in env is IGNORED when a
    # TPU plugin is pinned (see conftest), so force through jax.config
    r = _run("dcgan_amp.py", "--batch-size", "8", "--image-size", "32",
             "--iters", "6", "--opt-level", opt_level,
             "--force-cpu-devices", "1")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Loss_D" in r.stdout and "nan" not in r.stdout.lower()


def test_simple_distributed_runs():
    r = _run("simple_distributed.py")
    assert r.returncode == 0, r.stderr[-2000:]


def test_long_context_training_runs():
    """Ring-attention (zigzag) context-parallel LM training end to end
    on the 8-way mesh — the long-context recipe the reference cannot
    express (FMHA seq cap 512)."""
    r = _run("long_context_training.py", "--seq", "8192", "--steps", "2",
             "--force-cpu-devices", "8")
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("step")]
    assert len(lines) == 2, r.stdout
    losses = [float(ln.split("loss")[1].split()[0]) for ln in lines]
    assert all(l == l and abs(l) < 1e9 for l in losses), losses
    assert losses[1] < losses[0], losses


def test_train_with_monitor_runs(tmp_path):
    """ISSUE 2 tier-1 gate: the telemetry demo trains 3 steps on CPU
    and every metrics JSONL line validates against the monitor schema
    (required fields, finite values, monotonic steps)."""
    import json

    from apex_tpu import monitor

    jsonl = tmp_path / "metrics.jsonl"
    r = _run("train_with_monitor.py", "--steps", "3",
             "--jsonl", str(jsonl), "--force-cpu-devices", "1")
    assert r.returncode == 0, r.stderr[-2000:]
    records = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    # the stream interleaves full step records with ScalarWriter timer
    # tags; the schema governs the step records
    step_records = [rec for rec in records if "loss" in rec]
    assert len(step_records) == 3, records
    monitor.validate_records(step_records)  # raises on NaN/non-monotonic
    for rec in step_records:
        assert rec["tokens_per_sec"] > 0
        assert rec["step_time_ms"] > 0
    assert any("train-step-time" in rec for rec in records), \
        "Timers.write scalars missing from the JSONL stream"


def test_serve_gpt_runs_64_streams():
    """ISSUE 8 acceptance: the continuous-batching demo decodes N=64
    concurrent ragged streams on the CPU smoke config with ZERO
    steady-state recompiles — the script itself exits nonzero if the
    sentry tripped or any request failed to retire."""
    r = _run("serve_gpt.py", "--streams", "64",
             "--force-cpu-devices", "1")
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "serve_gpt: OK (zero steady-state recompiles)" in r.stdout
    assert "decoded 64 requests" in r.stdout


def test_serve_gpt_drain_path_64_streams():
    """ISSUE 14 satellite: the graceful-drain path (the SIGTERM
    handler's exact code, driven deterministically) at N=64 CPU —
    every live request finishes, the queued remainder rides the
    restorable snapshot, and the script exits nonzero if any live
    request is lost."""
    r = _run("serve_gpt.py", "--streams", "64", "--max-new", "8",
             "--drain-after-steps", "6", "--force-cpu-devices", "1")
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "serve_gpt: drain OK (no live request lost)" in r.stdout
    assert "restorable snapshot" in r.stdout
