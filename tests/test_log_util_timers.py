"""Satellite coverage (ISSUE 2): utils/log_util.py (env verbosity,
handler idempotence) and the fixed utils/timers.py blocking semantics +
unknown-name hardening."""

import logging
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.utils import log_util
from apex_tpu.utils.timers import Timers, _Timer


# ------------------------------ log_util ------------------------------

def test_set_logging_level_idempotent():
    """Calling set_logging_level twice must not duplicate handlers (the
    rank-info formatter would double every line)."""
    logger = logging.getLogger("apex_tpu")
    before = list(logger.handlers)
    try:
        log_util.set_logging_level("DEBUG")
        n1 = len(logger.handlers)
        log_util.set_logging_level("INFO")
        assert len(logger.handlers) == n1
        assert logger.level == logging.INFO
    finally:
        logger.handlers[:] = before


def test_rank_info_formatter_formats_without_mesh():
    from apex_tpu import RankInfoFormatter

    f = RankInfoFormatter("[%(rank_info)s] %(message)s")
    rec = logging.LogRecord("apex_tpu.x", logging.INFO, __file__, 1,
                            "hello", (), None)
    out = f.format(rec)
    assert out.endswith("hello") and "[" in out
    # idempotent: formatting the same record twice is stable
    assert f.format(rec) == out


def test_env_var_verbosity_applies_at_import():
    """APEX_TPU_VERBOSITY in the environment sets the package logger
    level at first import (checked in a fresh interpreter)."""
    code = ("import logging, apex_tpu.utils.log_util; "
            "import sys; "
            "sys.exit(0 if logging.getLogger('apex_tpu').level == "
            "logging.DEBUG else 1)")
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240,
        env={"APEX_TPU_VERBOSITY": "DEBUG", "JAX_PLATFORMS": "cpu",
             "PATH": "/usr/local/bin:/usr/bin:/bin",
             "PYTHONPATH": str(__import__("pathlib").Path(
                 __file__).resolve().parent.parent)})
    assert r.returncode == 0, r.stderr[-2000:]


def test_get_transformer_logger_strips_extension():
    lg = log_util.get_transformer_logger("module.py")
    assert lg.name == "module"


# ------------------------------ timers ------------------------------

def test_timer_block_calls_block_until_ready(monkeypatch):
    """The ISSUE 2 satellite fix: stop(block=True) must DRAIN execution
    (block_until_ready on live arrays), not merely iterate over them —
    otherwise 'blocked' timings measure dispatch."""
    calls = []

    class FakeArray:
        def block_until_ready(self):
            calls.append("blocked")

    monkeypatch.setattr(jax, "live_arrays",
                        lambda: [FakeArray(), FakeArray()])
    t = _Timer("x")
    t.start()
    t.stop(block=True)
    assert calls == ["blocked", "blocked"]


def test_timer_block_wall_clock_covers_execution():
    """End-to-end: a blocked stop on a dispatched computation reports a
    nonzero elapsed time and leaves the timer reusable."""
    t = Timers()
    t("step").start()
    x = jnp.ones((256, 256))
    y = (x @ x).sum()
    t("step").stop(block=True)
    assert y.block_until_ready() is not None
    assert t("step").elapsed(reset=True) > 0.0
    t("step").start()  # restartable after elapsed(reset=True)
    t("step").stop()


def test_timers_unknown_name_raises_clear_keyerror():
    t = Timers()
    t("fwd").start()
    t("fwd").stop()
    with pytest.raises(KeyError, match=r"unknown timer 'bwd'.*fwd"):
        t.log(["bwd"])
    with pytest.raises(KeyError, match="unknown timer"):
        t.write(["nope"], writer=None, iteration=0)
    # registry unpolluted by the failed lookups
    assert sorted(t.timers) == ["fwd"]
    with pytest.raises(KeyError, match=r"\(none\)"):
        Timers().log(["anything"])


def test_timers_log_and_write_still_work():
    class W:
        def __init__(self):
            self.rows = []

        def add_scalar(self, tag, value, step):
            self.rows.append((tag, value, step))

    t = Timers()
    t("fwd").start()
    t("fwd").stop()
    s = t.log(["fwd"], reset=False)
    assert "fwd" in s and "time (ms)" in s
    w = W()
    t.write(["fwd"], w, iteration=3)
    assert w.rows and w.rows[0][0] == "fwd-time" and w.rows[0][2] == 3
