"""Numerics flight recorder tests (ISSUE 4): tap op + TapState,
NaN/overflow provenance, cross-rank straggler timing, crash-dump
integrity, and — the acceptance criteria — that trace=None rebuilds
the identical pre-trace step and trace taps change NO training
numerics (bitwise-equal params)."""

import json
import os
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, monitor
from apex_tpu.monitor import trace
from apex_tpu.ops import _common as tapc
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M

ROOT = pathlib.Path(__file__).resolve().parent.parent


# ------------------------------ tap op ------------------------------

def test_tap_is_identity_without_context():
    """The load-bearing zero-cost contract: no active TapContext means
    tap() returns ITS INPUT OBJECT — nothing enters the trace."""
    x = jnp.ones((4, 4))
    assert tapc.tap(x, "anything") is x


def test_tap_stats_values():
    x = jnp.asarray([[1.0, -3.0], [2.0, 0.0]])
    s = np.asarray(tapc.tap_stats(x))
    np.testing.assert_allclose(s[0], 3.0)                    # absmax
    np.testing.assert_allclose(s[1], 0.0)                    # mean
    np.testing.assert_allclose(s[2], np.sqrt(14.0 / 4.0), rtol=1e-6)
    assert s[3] == 0.0
    bad = jnp.asarray([jnp.nan, jnp.inf, 1.0, -jnp.inf])
    sb = np.asarray(tapc.tap_stats(bad))
    assert sb[3] == 3.0          # the count stays finite and exact
    assert not np.isfinite(sb[0])


def test_tap_context_overflow_raises():
    ctx = tapc.TapContext(probes=trace.make_probes(1))
    with tapc.tap_context(ctx):
        tapc.tap(jnp.ones(3), "a")
        with pytest.raises(ValueError, match="max_taps"):
            tapc.tap(jnp.ones(3), "b")


# --------------------- make_train_step trace plane ---------------------

def _linear_problem():
    X = jnp.asarray(np.random.default_rng(0).normal(size=(32, 4)),
                    jnp.float32)
    Y = X @ jnp.asarray([[1.0], [-2.0], [0.5], [3.0]])

    def loss_fn(params, batch):
        x, y = batch
        h = tapc.tap(x @ params["w"], "dense")
        return jnp.mean((h - y) ** 2)

    return loss_fn, {"w": jnp.zeros((4, 1))}, (X, Y)


def _train(mesh, trace_arg, steps=4, amp_on=True):
    loss_fn, params0, batch = _linear_problem()
    amp_state = amp.initialize(opt_level="O0", loss_scale="dynamic") \
        if amp_on else None
    scaler = amp_state.loss_scalers[0] if amp_on else None
    opt = FusedAdam(lr=0.05, use_pallas=False)
    state = opt.init(params0)
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               trace=trace_arg)
    outs = None
    for _ in range(steps):
        outs = step(state, scaler, batch)
        state, scaler = outs[0], outs[1]
    return state, outs, step


def test_trace_off_is_the_pre_trace_step():
    """Default (trace=None): same output arity and bitwise-identical
    params as always — the byte-identity acceptance criterion, asserted
    against the taps-enabled run below."""
    mesh = M.initialize_model_parallel()
    state_off, outs_off, _ = _train(mesh, None)
    assert len(outs_off) == 3  # (opt_state, scaler, loss) — unchanged
    state_on, outs_on, step = _train(mesh, True)
    assert len(outs_on) == 4   # + TapState
    a = np.asarray(jax.device_get(state_off.params))
    b = np.asarray(jax.device_get(state_on.params))
    assert a.tobytes() == b.tobytes(), "trace taps changed numerics"
    assert step.tap_names() == ("dense",)
    ts = outs_on[-1]
    assert ts.fwd.shape == (1, 4) and ts.grad.shape == (1, 4)
    assert int(ts.first_bad_fwd) == -1 and int(ts.first_bad_grad) == -1
    assert float(ts.fwd[0, 0]) > 0 and float(ts.grad[0, 0]) > 0


def test_trace_grad_plane_is_unscaled():
    """Under dynamic loss scaling the tap's gradient plane reports
    UNSCALED magnitudes (comparable across scale changes)."""
    mesh = M.initialize_model_parallel()
    _, outs_scaled, _ = _train(mesh, True, steps=1, amp_on=True)
    _, outs_plain, _ = _train(mesh, True, steps=1, amp_on=False)
    g_scaled = np.asarray(outs_scaled[-1].grad)
    g_plain = np.asarray(outs_plain[-1].grad)
    np.testing.assert_allclose(g_scaled[0, 0], g_plain[0, 0], rtol=1e-5)


def test_trace_taps_reject_microbatching():
    mesh = M.initialize_model_parallel()
    loss_fn, params0, _ = _linear_problem()
    opt = FusedAdam(lr=0.05, use_pallas=False)
    with pytest.raises(ValueError, match="num_microbatches"):
        ddp.make_train_step(loss_fn, opt, mesh, num_microbatches=2,
                            trace=True)
    # the timing-only config composes with microbatching
    ddp.make_train_step(loss_fn, opt, mesh, num_microbatches=2,
                        trace=trace.TraceConfig(taps=False,
                                                rank_timing=True))


def test_bert_tap_points_discoverable():
    """BERT threads the same tap points; discovery mode (names only,
    no probes) enumerates them without running the model."""
    from apex_tpu.models.bert import Bert, BertConfig

    cfg = BertConfig(vocab_size=64, seq_len=16, hidden=32, num_layers=2,
                     num_heads=4)
    from jax import shard_map

    mesh = M.initialize_model_parallel()
    model = Bert(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    fn = shard_map(model.encode, mesh=mesh, in_specs=(P(), P()),
                   out_specs=P(), check_vma=False)
    ctx = tapc.TapContext(discover=True)
    with tapc.tap_context(ctx):
        jax.eval_shape(fn, params, tokens)
    assert ctx.names == [f"block{i}/{p}" for i in range(2)
                         for p in ("ln1", "attn", "ln2", "mlp")]


# ----------------------- NaN injection provenance -----------------------

def _tiny_gpt_step(params, trace_arg=True):
    from apex_tpu.models.gpt import GPT, GPTConfig

    mesh = M.initialize_model_parallel()
    cfg = GPTConfig(vocab_size=64, seq_len=16, hidden=32, num_layers=3,
                    num_heads=4)
    model = GPT(cfg)
    if params is None:
        params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-3, use_pallas=False)

    def loss_fn(p, batch):
        tokens, labels = batch
        return model.loss(p, tokens, labels)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    batch = (tokens, jnp.roll(tokens, -1, 1))
    step = ddp.make_train_step(loss_fn, opt, mesh,
                               batch_spec=(P("dp"), P("dp")),
                               trace=trace_arg)
    out = step(opt.init(params), None, batch)
    return params, out, step


def test_gpt_taps_bitwise_and_names():
    params, out_on, step = _tiny_gpt_step(None, trace_arg=True)
    _, out_off, _ = _tiny_gpt_step(params, trace_arg=None)
    a = np.asarray(jax.device_get(out_off[0].params))
    b = np.asarray(jax.device_get(out_on[0].params))
    assert a.tobytes() == b.tobytes()
    names = step.tap_names()
    assert len(names) == 3 * 4  # ln1/attn/ln2/mlp per block
    assert names[0] == "block0/ln1" and names[5] == "block1/attn"


def test_gpt_nan_injection_attributed_in_report(tmp_path):
    """ISSUE 4 acceptance: a seeded NaN at a known layer is attributed
    to that layer's tap in the DUMPED report."""
    from apex_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, seq_len=16, hidden=32, num_layers=3,
                    num_heads=4)
    params = GPT(cfg).init(jax.random.PRNGKey(0))
    # poison block1's attention projection: the first tap downstream of
    # it in forward order is block1/attn
    w = np.asarray(params["block1"]["proj"]["weight"]).copy()
    w[0, 0] = np.nan
    params["block1"]["proj"]["weight"] = jnp.asarray(w)

    _, out, step = _tiny_gpt_step(params, trace_arg=True)
    tap_state = out[-1]
    names = step.tap_names()
    prov = trace.provenance(tap_state, names)
    assert prov is not None
    assert prov["plane"] == "fwd" and prov["tap"] == "block1/attn"
    assert prov["stats"]["nonfinite"] > 0

    path = tmp_path / "flight.json"
    rec = trace.FlightRecorder(path, capacity=4, tap_names=names)
    rec.record(7, taps=tap_state)
    rep = rec.dump(reason="test")
    on_disk = json.loads(path.read_text())  # parseable despite NaNs
    trace.validate_report(on_disk)
    assert on_disk["records"][0]["taps"]["first_bad_fwd"] == "block1/attn"
    text = trace.render_report(on_disk)
    assert "block1/attn" in text and "first bad step: 7" in text


def test_amp_overflow_grad_plane_provenance():
    """A loss-scaling overflow (clean forward, non-finite scaled grads)
    attributes on the GRADIENT plane, and FP16_Optimizer surfaces it
    via overflow_provenance()."""
    big = 3e38  # finite in f32; the 2^16-scaled cotangent overflows

    def loss_fn(p, x):
        h = tapc.tap(x @ p["w"], "dense")
        return jnp.sum(h) * big

    from apex_tpu.amp.fp16_optimizer import FP16_Optimizer

    x = jnp.ones((4, 4))
    p = {"w": jnp.full((4, 1), 1e-3)}
    probes = trace.make_probes(4)
    fp16 = FP16_Optimizer(FusedAdam(lr=0.1, use_pallas=False),
                          dynamic_loss_scale=True)
    state = fp16.init(p)

    def scaled(p_probes, x):
        pp, pr = p_probes
        ctx = tapc.TapContext(probes=pr)
        with tapc.tap_context(ctx):
            loss = loss_fn(pp, x)
        return fp16.scale_loss(loss), tuple(ctx.names)

    (grads, probe_g), names = jax.grad(scaled, has_aux=True)((p, probes), x)
    tap_state = trace.finalize(probe_g, len(names))
    assert int(tap_state.first_bad_fwd) == -1   # forward was clean
    assert int(tap_state.first_bad_grad) == 0   # scaled cotangent: inf

    _, state, = fp16.step(state, grads, tap_state=tap_state,
                          tap_names=names)
    assert bool(fp16.scaler_state.found_inf)    # the skip happened
    prov = fp16.overflow_provenance()
    assert prov == {"plane": "grad", "tap": "dense", "index": 0,
                    "stats": prov["stats"]}
    assert prov["stats"]["nonfinite"] > 0


# -------------------------- cross-rank timing --------------------------

def test_straggler_detector_unit():
    det = trace.StragglerDetector(threshold=1.5, patience=2)
    even = np.full((4, 2), 0.1)
    s = det.update(even)
    assert s["skew"] == pytest.approx(1.0) and not s["flagged"]
    slow = even.copy()
    slow[2, 0] = 0.3
    det.update(slow)
    assert det.flagged_ranks == ()          # 1 outlier step < patience
    s = det.update(slow)
    assert det.flagged_ranks == (2,)
    assert s["flagged"][0]["skew"] == pytest.approx(3.0)
    assert s["max_rank"] == 2
    det.update(even)                        # recovery resets the count
    assert det.flagged_ranks == ()
    with pytest.raises(ValueError, match="threshold"):
        trace.StragglerDetector(threshold=1.0)


def test_train_step_rank_timing_flags_delayed_rank():
    """ISSUE 4 acceptance: >= 2 simulated dp shards, an artificially
    delayed rank is flagged with the correct rank id and skew, via ONE
    small all_gather per step."""
    mesh = M.initialize_model_parallel()
    dp = mesh.shape[M.DP_AXIS]
    assert dp >= 2
    loss_fn, params0, batch = _linear_problem()
    opt = FusedAdam(lr=0.05, use_pallas=False)
    state = opt.init(params0)
    cfg = trace.TraceConfig(taps=False, rank_timing=True)
    step = ddp.make_train_step(loss_fn, opt, mesh,
                               batch_spec=(P("dp"), P("dp")), trace=cfg)
    det = trace.StragglerDetector(threshold=1.5, patience=3)
    delayed = 3
    for _ in range(3):
        local = np.full((dp, 2), 0.1, np.float32)
        local[delayed, 0] = 0.35  # the artificial delay
        out = step(state, None, batch, jnp.asarray(local))
        state, gathered = out[0], out[-1]
        # the all_gather must replicate every rank's vector verbatim
        np.testing.assert_allclose(np.asarray(gathered), local)
        det.update(gathered)
    assert det.flagged_ranks == (delayed,)
    assert det.last["flagged"][0]["skew"] == pytest.approx(3.5)
    assert det.last["max_rank"] == delayed


def test_fbnp_rank_timing_gather():
    from jax import shard_map
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_no_pipelining)

    mesh = M.initialize_model_parallel()
    dp = mesh.shape[M.DP_AXIS]
    w = {"w": jnp.asarray([[2.0], [1.0]])}
    batch = jnp.asarray(np.random.default_rng(1).normal(size=(4, 8, 2)),
                        jnp.float32)

    def fwd(p, mb):
        return jnp.mean((mb @ p["w"]) ** 2)

    # legacy return shape untouched
    out = forward_backward_no_pipelining(fwd, batch, w, num_microbatches=4)
    assert len(out) == 2

    def local(params, b, timing):
        loss, grads, gathered = forward_backward_no_pipelining(
            fwd, b, params, num_microbatches=4,
            rank_timing=timing.reshape(-1))
        return loss, gathered

    timing = np.tile(np.asarray([[0.1, 0.02]], np.float32), (dp, 1))
    timing[1, 0] = 0.5
    fn = jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P(), P(), P("dp")),
        out_specs=(P(), P()), check_vma=False))
    _, gathered = fn(w, batch, jnp.asarray(timing))
    np.testing.assert_allclose(np.asarray(gathered), timing)


# ------------------------- recorder + report -------------------------

def test_flight_recorder_ring_and_guard(tmp_path):
    path = tmp_path / "r.json"
    rec = trace.FlightRecorder(path, capacity=3, tap_names=["a"])
    with pytest.raises(RuntimeError, match="boom"):
        with rec.guard():
            for i in range(5):
                rec.record(i, metrics={"step": i, "loss": float(i)})
            raise RuntimeError("boom")
    data = json.loads(path.read_text())
    trace.validate_report(data)
    assert data["reason"].startswith("exception: RuntimeError")
    assert [r["step"] for r in data["records"]] == [2, 3, 4]  # ring of 3
    assert len(rec) == 3
    with pytest.raises(ValueError, match="capacity"):
        trace.FlightRecorder(path, capacity=0)


def test_validate_report_rejects_drift(tmp_path):
    rec = trace.FlightRecorder(tmp_path / "r.json", capacity=2)
    rep = rec.report()
    trace.validate_report(rep)
    with pytest.raises(ValueError, match="flight_recorder_version"):
        trace.validate_report(dict(rep, flight_recorder_version=99))
    with pytest.raises(ValueError, match="missing report field"):
        trace.validate_report({k: v for k, v in rep.items()
                               if k != "tap_names"})


def test_logger_tap_summary_fields(tmp_path):
    ts = trace.TapState(
        fwd=jnp.asarray([[2.0, 0.0, 1.0, 0.0], [3.0, 0.0, 1.0, 0.0]]),
        grad=jnp.asarray([[0.5, 0.0, 0.1, 0.0],
                          [jnp.inf, jnp.nan, jnp.inf, 7.0]]),
        first_bad_fwd=jnp.asarray(-1, jnp.int32),
        first_bad_grad=jnp.asarray(1, jnp.int32))
    path = tmp_path / "m.jsonl"
    logger = monitor.MetricsLogger([monitor.JSONLSink(path)], taps=True)
    m = monitor.init_metrics()._replace(step=jnp.asarray(1, jnp.int32))
    rec = logger.log_step(m, taps=ts, tap_names=["l0", "l1"])
    assert rec["tap_fwd_absmax"] == 3.0
    assert rec["tap_nonfinite"] == 7.0
    assert rec["tap_first_bad"] == "l1"
    logger.close()
    (line,) = path.read_text().splitlines()
    disk = json.loads(line)  # inf serialized as null + marker
    assert disk["tap_grad_absmax"] is None
    assert disk["tap_grad_absmax_nonfinite"] == "inf"


# ----------------------- CLI + crash-dump gates -----------------------

def _run_script(path, *args, timeout=600):
    return subprocess.run(
        [sys.executable, str(path), *args], capture_output=True,
        text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_flight_report_selftest():
    """Tier-1 CI gate (mirrors `gpt_anatomy.py tune --check`): the
    committed fixture renders under the CURRENT schema."""
    r = _run_script(ROOT / "scripts" / "flight_report.py", "--selftest")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "flight_report --selftest: OK" in r.stdout


def test_crash_dump_integrity(tmp_path):
    """ISSUE 4 satellite: the demo path raises mid-loop; the dumped
    report must be complete, parseable JSON at the expected ring
    depth — and renderable."""
    report = tmp_path / "flight.json"
    r = _run_script(ROOT / "examples" / "train_with_monitor.py",
                    "--steps", "6", "--jsonl", str(tmp_path / "m.jsonl"),
                    "--flight-report", str(report),
                    "--flight-capacity", "4", "--crash-at", "3",
                    "--force-cpu-devices", "1")
    assert r.returncode != 0, "injected crash must propagate"
    assert "injected crash at step 3" in r.stderr
    data = json.loads(report.read_text())
    trace.validate_report(data)
    assert data["reason"].startswith("exception: RuntimeError")
    # steps 0..3 recorded, ring keeps the last 4
    assert [rec["step"] for rec in data["records"]] == [0, 1, 2, 3]
    for rec in data["records"]:
        assert rec["taps"] is not None and rec["timings"] is not None
    assert data["straggler"]["steps_seen"] == 4
    # the renderer consumes what the recorder wrote
    r2 = _run_script(ROOT / "scripts" / "flight_report.py", str(report))
    assert r2.returncode == 0, r2.stderr
    assert "no non-finite step in the recorded window" in r2.stdout
