"""Chunked compute/collective overlap numerics (ISSUE 18).

The contract parallel/overlap.py must keep: every chunked spelling is
allclose (fp32-accum, tolerance-pinned per dtype) to the monolithic
spelling for chunk counts {1, 2, 4} x (fwd, bwd) x (bf16, fp32), the
chunks == 1 path is BYTE-IDENTICAL to the pre-overlap program (it IS
the original code path — pinned here by lowered-HLO equality), and a
non-dividing chunk request falls back to the largest dividing count
with a single warning (the flash-attention block rule).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.moe import dispatch as D
from apex_tpu.moe import router as R
from apex_tpu.moe.layer import MoEMLP
from apex_tpu.parallel import mesh as M
from apex_tpu.parallel import overlap as OV
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
)

# tolerance per dtype: the chunked GEMMs contract the same rows with
# fp32 MXU accumulation, but XLA retiles the partials, so allow
# accumulation-order wobble (tight for fp32, one-ulp-ish for bf16)
_TOL = {jnp.float32: dict(rtol=3e-5, atol=3e-5),
        jnp.bfloat16: dict(rtol=3e-2, atol=3e-2)}

DTYPES = [jnp.float32, jnp.bfloat16]
CHUNKS = [1, 2, 4]


def _allclose(a, b, dtype):
    np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32),
        **_TOL[dtype])


def _tp_mesh(tp=4):
    M.destroy_model_parallel()
    return M.initialize_model_parallel(tensor_model_parallel_size=tp)


# ----------------------------- TP layers ------------------------------
#
# One runner per layer shape: build the layer at a given chunk count,
# run fwd + value_and_grad of a fixed linear probe loss INSIDE
# shard_map (the training-step convention — the custom_vjp collectives
# make per-shard grads of the global loss correct), compare every
# chunked result against the chunks=1 monolithic anchor.

def _run_layer(layer, specs, w, b, x, t, mesh):
    w_spec, b_spec, x_spec, y_spec = specs

    def local(w_l, b_l, x_l, t_l):
        def loss_fn(args):
            w_, b_, x_ = args
            y = layer.apply({"weight": w_, "bias": b_}, x_)
            return jnp.sum(y.astype(jnp.float32)
                           * t_l.astype(jnp.float32))
        loss, grads = jax.value_and_grad(loss_fn)((w_l, b_l, x_l))
        y = layer.apply({"weight": w_l, "bias": b_l}, x_l)
        return y, loss.reshape(1), grads

    f = shard_map(local, mesh=mesh,
                  in_specs=(w_spec, b_spec, x_spec, y_spec),
                  out_specs=((y_spec, P(),
                              (w_spec, b_spec, x_spec))),
                  check_vma=False)
    y, loss, (dw, db, dx) = jax.jit(f)(w, b, x, t)
    return y, loss, dw, db, dx


def _col_sp_case(chunks, dtype, tp=4, s_loc=8, bsz=2, h=16, o=32):
    mesh = _tp_mesh(tp)
    k = jax.random.PRNGKey(0)
    kw, kb, kx, kt = jax.random.split(k, 4)
    w = jax.random.normal(kw, (h, o), jnp.float32).astype(dtype)
    b = jax.random.normal(kb, (o,), jnp.float32).astype(dtype)
    x = jax.random.normal(kx, (tp * s_loc, bsz, h),
                          jnp.float32).astype(dtype)
    t = jax.random.normal(kt, (tp * s_loc, bsz, o), jnp.float32)
    lay = ColumnParallelLinear(h, o, sequence_parallel=True,
                               axis_name="tp", overlap_chunks=chunks)
    specs = (P(None, "tp"), P("tp"), P("tp"), P(None, None, "tp"))
    return _run_layer(lay, specs, w, b, x, t, mesh)


def _row_sp_case(chunks, dtype, tp=4, s=32, bsz=2, h=16, o=24):
    mesh = _tp_mesh(tp)
    k = jax.random.PRNGKey(1)
    kw, kb, kx, kt = jax.random.split(k, 4)
    w = jax.random.normal(kw, (h, o), jnp.float32).astype(dtype)
    b = jax.random.normal(kb, (o,), jnp.float32).astype(dtype)
    x = jax.random.normal(kx, (s, bsz, h), jnp.float32).astype(dtype)
    t = jax.random.normal(kt, (s, bsz, o), jnp.float32)
    lay = RowParallelLinear(h, o, sequence_parallel=True,
                            axis_name="tp", overlap_chunks=chunks)
    specs = (P("tp", None), P(), P(None, None, "tp"), P("tp"))
    return _run_layer(lay, specs, w, b, x, t, mesh)


def _row_ar_case(chunks, dtype, tp=4, s=16, bsz=2, h=16, o=24):
    mesh = _tp_mesh(tp)
    k = jax.random.PRNGKey(2)
    kw, kb, kx, kt = jax.random.split(k, 4)
    w = jax.random.normal(kw, (h, o), jnp.float32).astype(dtype)
    b = jax.random.normal(kb, (o,), jnp.float32).astype(dtype)
    x = jax.random.normal(kx, (s, bsz, h), jnp.float32).astype(dtype)
    t = jax.random.normal(kt, (s, bsz, o), jnp.float32)
    lay = RowParallelLinear(h, o, sequence_parallel=False,
                            axis_name="tp", overlap_chunks=chunks)
    specs = (P("tp", None), P(), P(None, None, "tp"), P())
    return _run_layer(lay, specs, w, b, x, t, mesh)


def _col_copy_case(chunks, dtype, tp=4, s=16, bsz=2, h=16, o=32):
    mesh = _tp_mesh(tp)
    k = jax.random.PRNGKey(3)
    kw, kb, kx, kt = jax.random.split(k, 4)
    w = jax.random.normal(kw, (h, o), jnp.float32).astype(dtype)
    b = jax.random.normal(kb, (o,), jnp.float32).astype(dtype)
    x = jax.random.normal(kx, (s, bsz, h), jnp.float32).astype(dtype)
    t = jax.random.normal(kt, (s, bsz, o), jnp.float32)
    lay = ColumnParallelLinear(h, o, sequence_parallel=False,
                               axis_name="tp", overlap_chunks=chunks)
    specs = (P(None, "tp"), P("tp"), P(), P(None, None, "tp"))
    return _run_layer(lay, specs, w, b, x, t, mesh)


_CASES = {"col_sp": _col_sp_case, "row_sp": _row_sp_case,
          "row_ar": _row_ar_case, "col_copy": _col_copy_case}


@pytest.mark.parametrize("dtype", DTYPES, ids=["fp32", "bf16"])
@pytest.mark.parametrize("case", sorted(_CASES))
def test_tp_chunked_allclose_monolithic(case, dtype):
    """fwd + bwd at chunks in {2, 4} allclose to the chunks=1 anchor
    for every TP layer shape; grads cover weight, bias AND input (the
    backward-direction collectives)."""
    run = _CASES[case]
    y1, l1, dw1, db1, dx1 = run(1, dtype)
    for c in (2, 4):
        yc, lc, dwc, dbc, dxc = run(c, dtype)
        _allclose(yc, y1, dtype)
        _allclose(lc, l1, dtype)
        _allclose(dwc, dw1, dtype)
        _allclose(dbc, db1, dtype)
        _allclose(dxc, dx1, dtype)


def test_chunks1_bitwise_and_byte_identical():
    """overlap_chunks=1, =None (tuner miss), and the knob simply not
    exercised are the SAME program: bitwise outputs and identical
    lowered HLO — the RecompileSentry/byte-identity anchor for
    untuned machines."""
    mesh = _tp_mesh(4)
    h, o, s_loc, bsz = 16, 32, 8, 2
    k = jax.random.PRNGKey(0)
    kw, kx = jax.random.split(k)
    w = jax.random.normal(kw, (h, o), jnp.float32)
    x = jax.random.normal(kx, (4 * s_loc, bsz, h), jnp.float32)

    def lowered(chunks):
        lay = ColumnParallelLinear(h, o, bias=False,
                                   sequence_parallel=True,
                                   axis_name="tp",
                                   overlap_chunks=chunks)
        f = jax.jit(shard_map(
            lambda w_, x_: lay.apply({"weight": w_}, x_), mesh=mesh,
            in_specs=(P(None, "tp"), P("tp")),
            out_specs=P(None, None, "tp"), check_vma=False))
        return f, f.lower(w, x).as_text()

    f1, hlo1 = lowered(1)
    fn, hlon = lowered(None)
    assert hlo1 == hlon
    assert np.array_equal(np.asarray(f1(w, x)), np.asarray(fn(w, x)))
    # and the monolithic program really is collective-permute-free
    # while chunks=2 trades its all-gather for ring ppermutes
    _, hlo2 = lowered(2)
    assert "all_gather" in hlo1 and "collective_permute" not in hlo1
    assert "collective_permute" in hlo2


def test_ring_bytes_drop_all_gather():
    """The ring spelling's HLO carries (p-1)*chunks collective-permutes
    and NO all-gather — the (p-1)/p-bytes claim is a program property,
    pinned here at the unit level (comms_probe pins the flagship)."""
    mesh = _tp_mesh(4)
    h, o, s_loc = 16, 32, 8
    w = jnp.ones((h, o), jnp.float32)
    x = jnp.ones((4 * s_loc, 2, h), jnp.float32)
    lay = ColumnParallelLinear(h, o, bias=False, sequence_parallel=True,
                               axis_name="tp", overlap_chunks=2)
    hlo = jax.jit(shard_map(
        lambda w_, x_: lay.apply({"weight": w_}, x_), mesh=mesh,
        in_specs=(P(None, "tp"), P("tp")),
        out_specs=P(None, None, "tp"),
        check_vma=False)).lower(w, x).as_text()
    assert "all_gather" not in hlo
    assert hlo.count("stablehlo.collective_permute") == (4 - 1) * 2


def test_non_dividing_chunks_fall_back_largest_divisor():
    """overlap_chunks=3 against 8 local rows: the layer runs at 2
    chunks (largest divisor), warns ONCE, and stays allclose."""
    OV._WARNED_SITES.clear()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        y3, l3, dw3, db3, dx3 = _col_sp_case(3, jnp.float32)
        _col_sp_case(3, jnp.float32)  # re-trace: no second warning
    msgs = [str(r.message) for r in rec
            if "overlap_chunks" in str(r.message)]
    assert len(msgs) == 1 and "falling back to 2" in msgs[0]
    y1, l1, dw1, db1, dx1 = _col_sp_case(1, jnp.float32)
    _allclose(y3, y1, jnp.float32)
    _allclose(dw3, dw1, jnp.float32)


def test_resolve_chunks_math():
    assert OV.resolve_chunks(1, 64) == 1
    assert OV.resolve_chunks(4, 64) == 4
    assert OV.resolve_chunks(5, 10, site="t-a") == 5
    OV._WARNED_SITES.clear()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert OV.resolve_chunks(7, 8, site="t-b") == 4
        assert OV.resolve_chunks(6, 9, site="t-c") == 3
        assert OV.resolve_chunks(3, 7, site="t-d") == 1
    assert len(rec) == 3


def test_tuner_owned_chunks_consult_cache(monkeypatch):
    """overlap_chunks=None asks tune.tuned('overlap_chunks', ...) with
    the overlap_attrs key; a planted config drives the chunk count."""
    from apex_tpu import tune
    seen = {}
    real = tune.tuned

    def fake(op, attrs=None, **kw):
        if op == "overlap_chunks":
            seen[attrs["path"]] = dict(attrs)
            return {"chunks": 2}
        return real(op, attrs, **kw)

    monkeypatch.setattr(tune, "tuned", fake)
    mesh = _tp_mesh(4)
    h, o = 16, 32
    w = jnp.ones((h, o), jnp.float32)
    x = jnp.ones((32, 2, h), jnp.float32)
    lay = ColumnParallelLinear(h, o, bias=False, sequence_parallel=True,
                               axis_name="tp", overlap_chunks=None)
    hlo = jax.jit(shard_map(
        lambda w_, x_: lay.apply({"weight": w_}, x_), mesh=mesh,
        in_specs=(P(None, "tp"), P("tp")),
        out_specs=P(None, None, "tp"),
        check_vma=False)).lower(w, x).as_text()
    assert "collective_permute" in hlo  # the planted chunks=2 ran
    assert seen["tp_col"]["ax"] == 4
    assert seen["tp_col"]["dtype"] == "float32"


# ------------------------------- MoE ----------------------------------

def test_moe_chunked_exchange_bitwise_rowwise():
    """dispatch.chunked_expert_exchange with a row-independent ffn is
    BITWISE the monolithic exchange at every chunk count (elementwise
    ffn → identical per-row values, exact reassembly), through the
    real ep=2 all_to_all pair."""
    e, h, t = 4, 8, 16
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(expert_model_parallel_size=2,
                                       devices=jax.devices()[:4])

    def f(xs, chunks):
        idx = (jnp.arange(xs.shape[0])[:, None] * 3) % e
        cap = R.expert_capacity(xs.shape[0], e, 1, float("inf"))
        dest, _ = R.capacity_destinations(idx, e, cap)
        buf = D.dispatch(xs, dest, e, cap)
        ybuf = D.chunked_expert_exchange(
            buf, lambda xe: xe * 2.0 + 1.0, "ep", 2, e, cap, chunks)
        return D.combine(ybuf, dest, jnp.ones((xs.shape[0], 1),
                                              jnp.float32))

    x = jax.random.normal(jax.random.PRNGKey(4), (16, h), jnp.float32)
    outs = [jax.jit(shard_map(
        lambda xs, c=c: f(xs, c), mesh=mesh,
        in_specs=(P(("dp", "ep")),), out_specs=P(("dp", "ep")),
        check_vma=False))(x) for c in CHUNKS]
    for c, out in zip(CHUNKS[1:], outs[1:]):
        assert np.array_equal(np.asarray(out), np.asarray(outs[0])), c


@pytest.mark.parametrize("dtype", DTYPES, ids=["fp32", "bf16"])
def test_moe_micro_chunk_allclose(dtype):
    """MoEMLP fwd + bwd at chunks {2, 4} vs the monolithic anchor on a
    dp x ep=2 mesh: outputs and (pmean'd) param grads allclose."""
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(expert_model_parallel_size=2,
                                       devices=jax.devices()[:4])
    hid, ffn, e = 16, 32, 4
    tloc = 16  # local tokens; cap = ceil(16*2*2/4) = 16, 4-divisible
    x = jax.random.normal(jax.random.PRNGKey(7), (2 * 2 * tloc, hid),
                          jnp.float32).astype(dtype)
    t = jax.random.normal(jax.random.PRNGKey(8), x.shape, jnp.float32)

    def run(chunks):
        moe = MoEMLP(hid, ffn, e, top_k=2, capacity_factor=2.0,
                     ep_size=2, overlap_chunks=chunks)
        params = moe.init(jax.random.PRNGKey(0), dtype)

        def local(p, x_l, t_l):
            def loss_fn(p_):
                y, _aux = moe.apply(p_, x_l)
                return jnp.sum(y.astype(jnp.float32)
                               * t_l.astype(jnp.float32))
            loss, grads = jax.value_and_grad(loss_fn)(p)
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, ("dp", "ep")), grads)
            y, _ = moe.apply(p, x_l)
            return y, lax.psum(loss, ("dp", "ep")).reshape(1), grads

        f = shard_map(local, mesh=mesh,
                      in_specs=(P(), P(("dp", "ep")), P(("dp", "ep"))),
                      out_specs=(P(("dp", "ep")), P(), P()),
                      check_vma=False)
        return jax.jit(f)(params, x, t)

    y1, l1, g1 = run(1)
    for c in (2, 4):
        yc, lc, gc = run(c)
        _allclose(yc, y1, dtype)
        _allclose(lc, l1, dtype)
        for k in g1:
            _allclose(gc[k], g1[k], dtype)


def test_moe_chunks1_byte_identical():
    """MoEMLP at overlap_chunks=1 vs =None (tuner miss) lower to the
    same HLO — the untuned-path anchor for the exchange."""
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(expert_model_parallel_size=2,
                                       devices=jax.devices()[:4])
    hid, ffn, e = 16, 32, 4
    x = jnp.ones((64, hid), jnp.float32)

    def lowered(chunks):
        moe = MoEMLP(hid, ffn, e, top_k=2, capacity_factor=2.0,
                     ep_size=2, overlap_chunks=chunks)
        params = moe.init(jax.random.PRNGKey(0), jnp.float32)
        f = jax.jit(shard_map(
            lambda p, x_l: moe.apply(p, x_l)[0], mesh=mesh,
            in_specs=(P(), P(("dp", "ep"))),
            out_specs=P(("dp", "ep")), check_vma=False))
        return f.lower(params, x).as_text()

    assert lowered(1) == lowered(None)


def test_moe_chunked_all_to_all_inventory():
    """chunks=2 doubles the all-to-all count at half the rows each —
    chunk-count-many smaller collectives, same total payload (the
    comms-fixture pin, unit-level)."""
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(expert_model_parallel_size=2,
                                       devices=jax.devices()[:4])
    hid, ffn, e = 16, 32, 4
    x = jnp.ones((64, hid), jnp.float32)

    def count_a2a(chunks):
        moe = MoEMLP(hid, ffn, e, top_k=2, capacity_factor=2.0,
                     ep_size=2, overlap_chunks=chunks)
        params = moe.init(jax.random.PRNGKey(0), jnp.float32)
        hlo = jax.jit(shard_map(
            lambda p, x_l: moe.apply(p, x_l)[0], mesh=mesh,
            in_specs=(P(), P(("dp", "ep"))),
            out_specs=P(("dp", "ep")), check_vma=False)
        ).lower(params, x).as_text()
        return hlo.count("stablehlo.all_to_all")

    n1, n2 = count_a2a(1), count_a2a(2)
    assert n1 > 0 and n2 == 2 * n1
