"""Scratch: A/B pallas LN vs XLA LN in full step; batch16+dots remat."""
import sys, time
import jax, jax.numpy as jnp
import numpy as np


def run(tag, batch, remat=False, remat_policy=None, no_pallas_ln=False):
    if no_pallas_ln:
        import apex_tpu.ops.layer_norm as LN
        orig = LN.fused_layer_norm
        LN.fused_layer_norm = lambda x, w=None, b=None, eps=1e-5, **kw: \
            LN.layer_norm_reference(x, w, b, eps)
        import apex_tpu.models.gpt as G
        G.fused_layer_norm = LN.fused_layer_norm
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import init_sharded_optimizer, make_tp_dp_train_step
    seq = 1024
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                    num_layers=24, num_heads=16, dropout=0.0,
                    dtype=jnp.bfloat16, remat=remat, remat_policy=remat_policy,
                    use_flash_attention=True)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=True)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    del params
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 50304)
    labels = jnp.roll(tokens, -1, axis=1)
    for _ in range(3):
        opt_state, loss = step(opt_state, tokens, labels)
    _ = np.asarray(loss)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(8):
            opt_state, loss = step(opt_state, tokens, labels)
        _ = np.asarray(loss)
        best = min(best, (time.perf_counter() - t0) / 8)
    print(f"{tag}: {best*1e3:7.1f} ms -> {batch*seq/best:,.0f} tok/s", flush=True)


if __name__ == "__main__":
    mode = sys.argv[1]
    if mode == "xla_ln":
        run("xla-ln  b8", 8, no_pallas_ln=True)
    elif mode == "b16dots":
        run("b16 dots", 16, remat=True, remat_policy="dots")
    elif mode == "base":
        run("base b8", 8)
