"""Scratch: step anatomy fwd vs fwd+bwd vs full step (delete after)."""
import time
import jax, jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import GPT, GPTConfig
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.parallel import mesh as M
from apex_tpu.transformer.training import init_sharded_optimizer, make_tp_dp_train_step
from apex_tpu.optimizers import flat as F


def t_loop(fn, args, iters=10, meas=3):
    out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0].ravel()[0])
    best = float("inf")
    for _ in range(meas):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0].ravel()[0])
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def main():
    seq, batch = 1024, 8
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                    num_layers=24, num_heads=16, dropout=0.0,
                    dtype=jnp.bfloat16, remat=False, use_flash_attention=True)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=True)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 50304)
    labels = jnp.roll(tokens, -1, axis=1)

    specs = model.partition_specs()
    fwd = jax.jit(shard_map(model.loss, mesh=mesh,
                            in_specs=(specs, P(), P()), out_specs=P(),
                            check_vma=False))
    print(f"fwd loss        : {t_loop(fwd, (params, tokens, labels))*1e3:7.1f} ms", flush=True)

    def fb(p, t, l):
        return jax.value_and_grad(lambda pp: model.loss(pp, t, l))(p)
    fbj = jax.jit(shard_map(fb, mesh=mesh, in_specs=(specs, P(), P()),
                            out_specs=(P(), specs), check_vma=False))
    print(f"fwd+bwd         : {t_loop(fbj, (params, tokens, labels))*1e3:7.1f} ms", flush=True)

    # fwd+bwd from flat params (incl unflatten + grads as leaves)
    def fb_flat(flatp, t, l):
        p = F.unflatten(flatp, opt.spec)
        return jax.value_and_grad(lambda pp: model.loss(pp, t, l))(p)
    fbf = jax.jit(shard_map(fb_flat, mesh=mesh,
                            in_specs=(P(("pp", "tp")), P(), P()),
                            out_specs=(P(), specs), check_vma=False))
    print(f"fwd+bwd w/unflat: {t_loop(fbf, (opt_state.params, tokens, labels))*1e3:7.1f} ms", flush=True)

    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    del params
    for _ in range(3):
        opt_state, loss = step(opt_state, tokens, labels)
    _ = np.asarray(loss)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            opt_state, loss = step(opt_state, tokens, labels)
        _ = np.asarray(loss)
        best = min(best, (time.perf_counter() - t0) / 10)
    print(f"full step       : {best*1e3:7.1f} ms -> {batch*seq/best:,.0f} tok/s", flush=True)


if __name__ == "__main__":
    main()
