"""Scratch: batch-size sweep for the bench config (delete after)."""
import sys, time
import jax, jax.numpy as jnp
import numpy as np

def run(batch, remat=False):
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import init_sharded_optimizer, make_tp_dp_train_step
    seq = 1024
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                    num_layers=24, num_heads=16, dropout=0.0,
                    dtype=jnp.bfloat16, remat=remat,
                    use_flash_attention=True)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=True)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    del params
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 50304)
    labels = jnp.roll(tokens, -1, axis=1)
    for _ in range(3):
        opt_state, loss = step(opt_state, tokens, labels)
    _ = np.asarray(loss)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(8):
            opt_state, loss = step(opt_state, tokens, labels)
        _ = np.asarray(loss)
        best = min(best, (time.perf_counter() - t0) / 8)
    print(f"batch={batch} remat={remat}: {best*1e3:7.1f} ms -> {batch*seq/best:,.0f} tok/s", flush=True)

if __name__ == "__main__":
    for b in sys.argv[1:]:
        if b.endswith("r"):
            run(int(b[:-1]), remat=True)
        else:
            run(int(b))
