"""Fused multihead-attention latency benchmark.

≡ apex/contrib/examples/multihead_attn/perf_test_multihead_attn.py:
101-110 — fwd and fwd+bwd latency of the fused self-attention module vs
an unfused jnp reference, on one chip.

Run:  python examples/bench_multihead_attn.py [--seq 1024] [--batch 8]
"""

from __future__ import annotations

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn


def timeit(f, *args, iters=20):
    for _ in range(3):
        r = f(*args)
    np.asarray(jax.tree_util.tree_leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        r = f(*args)
    np.asarray(jax.tree_util.tree_leaves(r)[0].ravel()[0])
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--heads", type=int, default=16)
    args = ap.parse_args()
    on_tpu = jax.default_backend() not in ("cpu",)
    if not on_tpu:
        args.seq, args.batch = 128, 2

    dt = jnp.bfloat16 if on_tpu else jnp.float32
    mha_fused = SelfMultiheadAttn(args.hidden, args.heads,
                                  impl="fast")   # flash-attention core
    mha_ref = SelfMultiheadAttn(args.hidden, args.heads, impl="default")
    p = mha_fused.init(jax.random.PRNGKey(0), dtype=dt)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (args.seq, args.batch, args.hidden), dt)

    fwd_fused = jax.jit(lambda p, x: mha_fused.apply(p, x))
    fwd_ref = jax.jit(lambda p, x: mha_ref.apply(p, x))

    def loss(fn):
        return jax.jit(jax.grad(
            lambda p, x: fn(p, x).astype(jnp.float32).sum()))

    bwd_fused, bwd_ref = loss(mha_fused.apply), loss(mha_ref.apply)

    res = {
        "metric": "self_mha_latency_ms",
        "config": f"seq{args.seq} b{args.batch} h{args.hidden}",
        "fused_fwd_ms": round(timeit(fwd_fused, p, x), 3),
        "ref_fwd_ms": round(timeit(fwd_ref, p, x), 3),
        "fused_fwdbwd_ms": round(timeit(bwd_fused, p, x), 3),
        "ref_fwdbwd_ms": round(timeit(bwd_ref, p, x), 3),
    }
    res["value"] = res["fused_fwdbwd_ms"]
    res["unit"] = "ms"
    res["vs_baseline"] = round(res["ref_fwdbwd_ms"] /
                               max(res["fused_fwdbwd_ms"], 1e-9), 2)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
