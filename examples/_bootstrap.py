"""Shared example bootstrap (import before jax in an example's header).

Importing this module puts the repo root on sys.path (the examples run
as plain scripts, unpip-installed) and provides the one flag that must
act BEFORE the first JAX backend use:

    --force-cpu-devices N   run on N emulated CPU devices

A session may pin a TPU plugin that IGNORES the JAX_PLATFORMS env var,
so the only reliable override is jax.config before backend init — the
same bootstrap tests/conftest.py uses.  The flag is left in sys.argv so
the example's argparse can document and record it.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _flag_value():
    for i, a in enumerate(sys.argv):
        if a == "--force-cpu-devices":
            if i + 1 >= len(sys.argv):
                sys.exit("--force-cpu-devices requires an integer value")
            return sys.argv[i + 1]
        if a.startswith("--force-cpu-devices="):
            return a.split("=", 1)[1]
    return None


def force_cpu_devices_from_argv():
    """Read --force-cpu-devices N (or =N) from sys.argv and act on it;
    no-op if absent or 0.  The flag is deliberately LEFT in sys.argv
    (module docstring) so the example's argparse can document and
    record it."""
    raw = _flag_value()
    if raw is None:
        return
    try:
        n = int(raw)
    except ValueError:
        sys.exit(f"--force-cpu-devices requires an integer value, "
                 f"got {raw!r}")
    if n <= 0:
        return
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)
