"""Shared example bootstrap (import before jax in an example's header).

Importing this module puts the repo root on sys.path (the examples run
as plain scripts, unpip-installed) and provides the one flag that must
act BEFORE the first JAX backend use:

    --force-cpu-devices N   run on N emulated CPU devices

A session may pin a TPU plugin that IGNORES the JAX_PLATFORMS env var,
so the only reliable override is jax.config before backend init — the
same bootstrap tests/conftest.py uses.  The flag is left in sys.argv so
the example's argparse can document and record it.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _flag_value():
    for i, a in enumerate(sys.argv):
        if a == "--force-cpu-devices":
            if i + 1 >= len(sys.argv):
                sys.exit("--force-cpu-devices requires an integer value")
            return sys.argv[i + 1]
        if a.startswith("--force-cpu-devices="):
            return a.split("=", 1)[1]
    return None


def force_cpu_devices_from_argv():
    """Read --force-cpu-devices N (or =N) from sys.argv and act on it;
    no-op if absent or 0.  The flag is deliberately LEFT in sys.argv
    (module docstring) so the example's argparse can document and
    record it."""
    raw = _flag_value()
    if raw is None:
        return
    try:
        n = int(raw)
    except ValueError:
        sys.exit(f"--force-cpu-devices requires an integer value, "
                 f"got {raw!r}")
    if n <= 0:
        return
    # jax 0.4.x has no jax_num_cpu_devices option — there the device
    # count comes from XLA_FLAGS, which must be in the environment
    # BEFORE the first jax import (the same dual path as
    # tests/conftest.py).  Set it unconditionally: on newer jax it is
    # harmlessly redundant with the config update below.
    if "jax" not in sys.modules:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # jax 0.4.x: the XLA_FLAGS set above provides the device count
        # — unless jax was already imported (flags too late) or the
        # environment pre-set its own count (respected: it may be the
        # caller's, e.g. the 8-way test harness satisfying a request
        # for 1).  Fail loudly only when FEWER devices than requested
        # are available — silently running under-parallel is the bug.
        if jax.device_count() < n:
            sys.exit(
                f"--force-cpu-devices {n}: this jax has no "
                f"jax_num_cpu_devices option and the XLA_FLAGS fallback "
                f"could not apply (jax already imported? devices="
                f"{jax.device_count()})")
