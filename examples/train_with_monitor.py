"""Train the minimal GPT with full telemetry (ISSUE 2 + ISSUE 4 demo).

The smallest end-to-end `apex_tpu.monitor` loop: a tiny GPT trains with
the fused data-parallel step (`ddp.make_train_step`) under dynamic loss
scaling, with a `MetricsState` riding inside the jitted program.  The
host logs every step to a metrics JSONL (schema-validated in
tests/test_examples.py) + console, with step time, tokens/sec, and MFU
derived by `MetricsLogger`; phase timers land in the same stream via
`Timers.write(names, logger.writer, step)`; `--profile-dir` arms a
`jax.profiler` capture over steps 1-2.

`--flight-report PATH` arms the numerics flight recorder (ISSUE 4):
the step is built with `trace=True` (per-layer stat taps + cross-rank
timing), every step lands in a bounded ring buffer, and any exception
in the loop dumps a JSON crash report to PATH (render with
`scripts/flight_report.py PATH`).  `--crash-at N` raises mid-loop at
step N to exercise exactly that path (the crash-dump integrity test,
tests/test_trace.py).

`--profile-steps A:B` arms a `ProfileCapture` over steps [A, B) and,
after the loop, parses the trace it wrote with the runtime timeline
observatory (ISSUE 15): the measured per-step anatomy table prints
(device-busy fraction, host gap, category split), the last records
stamp the `timeline_*` SCHEMA fields, and the script exits nonzero if
the trace parsed to zero device events — the tier-1 gate that the
capture → parse → anatomy loop stays wired end to end.

`--ckpt-dir PATH` arms preemption-proof checkpointing (ISSUE 9): a
`checkpoint.CheckpointManager` saves the optimizer + scaler state
every `--ckpt-every` steps (async, atomic-manifest commit), the logger
stamps the ckpt_* cadence-pricing fields into the same JSONL, and
`--resume` restores the latest COMMITTED step before training — run,
kill, re-run with --resume and the loss trajectory continues where the
last commit left it.

  python examples/train_with_monitor.py --steps 10 \\
      --jsonl /tmp/metrics.jsonl [--profile-dir /tmp/trace] \\
      [--flight-report /tmp/flight.json [--crash-at N]] \\
      [--ckpt-dir /tmp/ckpt [--ckpt-every N] [--resume]] \\
      [--force-cpu-devices N]
"""
import _bootstrap

_bootstrap.force_cpu_devices_from_argv()

import argparse

import jax
import jax.numpy as jnp

from apex_tpu import amp, monitor
from apex_tpu.models.gpt import GPT, GPTConfig
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M
from apex_tpu.utils.timers import Timers


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--jsonl", default="/tmp/train_with_monitor.jsonl")
    ap.add_argument("--profile-dir", default=None,
                    help="arm profile_capture over steps 1-2, traces here")
    ap.add_argument("--profile-steps", default=None, metavar="A:B",
                    help="capture steps [A, B) and print the measured "
                         "timeline anatomy after the loop (traces land "
                         "in --profile-dir or a temp dir)")
    ap.add_argument("--flight-report", default=None,
                    help="arm the numerics flight recorder; crash "
                         "report JSON dumps here")
    ap.add_argument("--flight-capacity", type=int, default=8,
                    help="flight-recorder ring depth (steps)")
    ap.add_argument("--crash-at", type=int, default=None,
                    help="raise mid-loop at this step (crash-dump demo)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="arm async checkpointing; committed steps "
                         "land under this directory")
    ap.add_argument("--ckpt-every", type=int, default=5,
                    help="checkpoint cadence in steps")
    ap.add_argument("--resume", action="store_true",
                    help="restore the latest committed checkpoint "
                         "from --ckpt-dir before training")
    ap.add_argument("--force-cpu-devices", type=int, default=None,
                    help="handled by _bootstrap before jax init")
    args = ap.parse_args()

    mesh = M.initialize_model_parallel()
    dp = mesh.shape[M.DP_AXIS]
    if args.batch % dp:
        raise SystemExit(f"--batch {args.batch} not divisible by dp={dp}")

    cfg = GPTConfig(vocab_size=128, seq_len=32, hidden=64, num_layers=2,
                    num_heads=4, dropout=0.0)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))

    # dynamic loss scaling exercises the scale/overflow telemetry even
    # in this fp32 CPU config (the scaler state is precision-agnostic)
    amp_state = amp.initialize(opt_level="O0", loss_scale="dynamic")
    scaler = amp_state.loss_scalers[0]

    opt = FusedAdam(lr=1e-3, use_pallas=False)
    opt_state = opt.init(params)

    # preemption-proof checkpointing (ISSUE 9): async sharded saves on
    # a cadence, resume from the latest COMMITTED step.  This demo's
    # FusedAdam is replicated (the manager writes one shard); the
    # ZeRO-2 optimizers persist per-rank shards through the same call.
    manager = None
    start_step = 0  # saves number from here: a resumed run must NOT
    # restart at step 1 and overwrite pre-kill commits with later state
    if args.ckpt_dir:
        from apex_tpu.checkpoint import CheckpointManager
        manager = CheckpointManager(args.ckpt_dir, opt,
                                    every_n_steps=args.ckpt_every)
        # the restore itself happens AFTER the warmup steps below —
        # warmup exists to absorb compiles, and running it on the
        # restored state would inject two extra optimizer updates per
        # preempt/resume cycle (the trajectory would silently drift
        # from the committed step)

    def loss_fn(p, batch):
        tokens, labels = batch
        return model.loss(p, tokens, labels)

    from jax.sharding import PartitionSpec as P
    flight = args.flight_report is not None
    trace_cfg = None
    if flight:
        trace_cfg = monitor.TraceConfig(taps=True, rank_timing=True)
    step = ddp.make_train_step(loss_fn, opt, mesh,
                               amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               metrics=True, trace=trace_cfg)

    recorder = None
    if flight:
        recorder = monitor.FlightRecorder(
            args.flight_report, capacity=args.flight_capacity,
            straggler=monitor.StragglerDetector())

    # the compile & HBM observatory (ISSUE 5): the sentry counts
    # traces/compiles (events land in the flight-recorder ring), the
    # logger stamps n_compiles + the hbm_* watermarks (null on CPU —
    # schema-legal) into every record
    sentry = monitor.RecompileSentry(step, recorder=recorder)
    step = sentry

    tokens_per_step = args.batch * cfg.seq_len
    # MFU convention: GLOBAL-batch FLOPs over the AGGREGATE peak of all
    # dp chips — without the dp factor a multi-chip run reads dp-times
    # too high (each chip computes 1/dp of the global FLOPs).
    # device_peak_flops() resolves the per-chip peak from the device
    # kind (v4/v5e/v5p table; V5E fallback elsewhere).
    logger = monitor.MetricsLogger(
        [monitor.JSONLSink(args.jsonl), monitor.ConsoleSink()],
        flops_per_step=monitor.gpt_step_flops(cfg, args.batch),
        peak_flops=monitor.device_peak_flops() * dp,
        taps=flight, sentry=sentry, memory=True, ckpt=manager)
    metrics = monitor.init_metrics()
    timers = Timers()

    if args.profile_steps:
        import tempfile
        try:
            a, b = (int(x) for x in args.profile_steps.split(":"))
        except ValueError:
            raise SystemExit(
                f"--profile-steps wants A:B, got {args.profile_steps!r}")
        if b <= a:
            raise SystemExit("--profile-steps A:B needs A < B")
        cap = monitor.profile_capture(
            range(a, b), logdir=args.profile_dir
            or tempfile.mkdtemp(prefix="train_with_monitor_trace_"))
    elif args.profile_dir:
        cap = monitor.profile_capture(range(1, 3),
                                      logdir=args.profile_dir)
    else:
        cap = monitor.ProfileCapture(())

    key = jax.random.PRNGKey(1)

    def make_batch(key):
        key, sub = jax.random.split(key)
        tokens = jax.random.randint(sub, (args.batch, cfg.seq_len), 0,
                                    cfg.vocab_size)
        return key, (tokens, jnp.roll(tokens, -1, axis=1))

    import time

    import numpy as np

    # the flight recorder's cross-rank timing plane: the host feeds
    # each step the PREVIOUS step's per-rank durations (this
    # single-process demo measures one wall clock for all dp shards;
    # multi-process launchers feed each process's own measurements)
    def run_step(batch, metrics, timing_row):
        if not flight:
            return step(opt_state_box[0], scaler_box[0], batch,
                        metrics) + (None, None)
        local_timing = jnp.asarray(
            np.tile(np.asarray(timing_row, np.float32), (dp, 1)))
        return step(opt_state_box[0], scaler_box[0], batch, metrics,
                    local_timing)

    opt_state_box = [opt_state]
    scaler_box = [scaler]
    prev_durations = (0.0, 0.0)

    if flight:
        # AOT compile audit of the exact step about to run (compiles
        # without executing): the crash dump then carries the HBM
        # budget table — the OOM-forensics payload.  lint=True also
        # attaches the static program passes' verdict (apex_tpu.lint),
        # so a crash dies with its lint findings alongside the budget
        try:
            _, audit_batch = make_batch(jax.random.PRNGKey(0))
            audit_args = (opt_state_box[0], scaler_box[0], audit_batch,
                          metrics,
                          jnp.asarray(np.tile(
                              np.asarray(prev_durations, np.float32),
                              (dp, 1))))
            recorder.attach_compile_report(monitor.analyze_step(
                sentry, audit_args,
                analytic_flops=monitor.gpt_step_flops(cfg, args.batch),
                lint=True, comms=True))
        except Exception as e:  # audit is advisory, never fatal
            print(f"compile audit unavailable: {e!r}")

    # two unlogged warmup steps, then restart the rate window: without
    # them the first record's step_time/tokens-per-sec/MFU measure jit
    # compilation, not training (two because the first donated-state
    # call can trigger a second compile when output layouts differ from
    # the initial inputs — same reason bench.py warms up twice)
    for _ in range(2):
        key, batch = make_batch(key)
        out = run_step(batch, metrics, prev_durations)
        opt_state_box[0], scaler_box[0], _, metrics = out[:4]
    jax.block_until_ready(opt_state_box[0])
    if manager is not None and args.resume:
        # restore only now, with the compiles already paid on throwaway
        # state: the resumed trajectory continues EXACTLY from the
        # committed step (same shapes/shardings — nothing retraces)
        if manager.last_committed_step is not None:
            opt_state_box[0], restored_scaler, manifest = \
                manager.restore(mesh)
            if restored_scaler is not None:
                scaler_box[0] = restored_scaler
            start_step = int(manifest["step"])
            # model state rides the SAME manifest (ISSUE 11): restore
            # the data-stream RNG key so the resumed run consumes the
            # batches the preempted one never saw — one commit covers
            # the whole run, nothing goes through a side channel
            model_state = manager.restore_model_state(step=start_step)
            if "rng_key" in model_state:
                key = jnp.asarray(model_state["rng_key"])
            print(f"resumed from committed checkpoint step {start_step}")
        else:
            print(f"--resume: no committed checkpoint under "
                  f"{args.ckpt_dir}; starting fresh")
    logger.reset_timer(metrics)  # resync step/token baselines too
    sentry.mark_steady()  # compiles were expected until here; any
    # further one is a silent retrace — warned once, visible as
    # n_compiles in the JSONL and as an event in the flight ring

    with (recorder.guard() if flight else cap):
        for i in range(args.steps):
            key, (tokens, labels) = make_batch(key)
            t0 = time.perf_counter()
            with cap.step(i):
                timers("train-step").start()
                out = run_step((tokens, labels), metrics, prev_durations)
                opt_state_box[0], scaler_box[0], loss, metrics = out[:4]
                tap_state, rank_timings = out[4], out[5]
                timers("train-step").stop(block=True)
            prev_durations = (time.perf_counter() - t0, 0.0)
            if args.profile_steps and logger.timeline is None \
                    and not cap.active:
                # the capture window just closed mid-run: parse the
                # trace NOW so the remaining records stamp the v11
                # timeline_* fields (trace_path() is None until the
                # window fired — early steps skip this at the cost of
                # a directory scan)
                _tp = cap.trace_path()
                if _tp is not None:
                    logger.timeline = monitor.analyze_trace(_tp)
            rec = logger.log_step(
                metrics, taps=tap_state,
                tap_names=step.tap_names() if flight else None)
            if recorder is not None:
                recorder.record(i, metrics=rec, taps=tap_state,
                                timings=rank_timings,
                                tap_names=step.tap_names())
            timers.write(["train-step"], logger.writer, i, reset=True)
            if manager is not None:
                manager.maybe_save(start_step + i + 1, opt_state_box[0],
                                   scaler_box[0],
                                   model_state={"rng_key":
                                                np.asarray(key)})
            if args.crash_at is not None and i == args.crash_at:
                raise RuntimeError(
                    f"injected crash at step {i} (--crash-at)")
    cap.close()
    if args.profile_steps:
        rep = logger.timeline
        if rep is None:
            tp = cap.trace_path()
            if tp is None:
                raise SystemExit(
                    "--profile-steps: no trace was captured — does the "
                    "window overlap [0, --steps)?")
            rep = monitor.analyze_trace(tp)
        print(monitor.render_timeline_table(
            rep, label=f"steps {args.profile_steps}"))
        if rep.n_device_events == 0:
            raise SystemExit(
                "--profile-steps: the trace parsed to ZERO device "
                "events — the capture wiring is broken")
    if manager is not None:
        manager.wait()
        print(f"last committed checkpoint: step "
              f"{manager.last_committed_step}")
    logger.close()
    print(f"wrote {args.steps} metric records to {args.jsonl} "
          f"({tokens_per_step} tokens/step)")
    if recorder is not None:
        recorder.dump(reason="run completed")
        print(f"flight report at {args.flight_report}")


if __name__ == "__main__":
    main()
