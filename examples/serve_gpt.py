"""Serve a GPT checkpoint with continuous batching (ISSUE 8).

Decodes N concurrent ragged-length streams through `apex_tpu.serve`:
paged KV cache, flash-decode attention, fixed-shape slot grid.  The
engine's RecompileSentry is the correctness gate — this script EXITS
NONZERO if admission/retirement churn ever retraced the steady-state
decode step, so CI holds the "shapes never change" contract
(docs/serving.md), not just the throughput number.

usage:
  python examples/serve_gpt.py                       # 64 streams
  python examples/serve_gpt.py --streams 256 --max-new 32
  python examples/serve_gpt.py --force-cpu-devices 1 # CPU smoke
  python examples/serve_gpt.py --slo-ttft-p99-ms 500 \
      --slo-token-p99-ms 50        # exit nonzero on an SLO breach

Besides the recompile gate, the run prints the request-lifecycle
ledger summary (TTFT / queue-wait percentiles, pool-utilization peak
— apex_tpu.serve.telemetry, ISSUE 10) and, when `--slo-*` thresholds
are given, exits nonzero on a `ServeSLO` breach verdict with the
violated axis named — the same posture as the sentry trip.

Resilience (ISSUE 14): `--deadline-ms` attaches a TTL to every
request (expired ones are evicted, terminal state `expired`), and the
process installs a SIGTERM handler that runs the GRACEFUL DRAIN path
— stop admission, finish live slots, snapshot the queued remainder —
and exits nonzero if any live request was lost to a non-ok terminal.
`--drain-after-steps N` triggers the same path deterministically
after N engine steps (the tier-1 CI gate for the drain path; sending
a real SIGTERM mid-run exercises the identical code).

On a CPU backend the smoke-size model substitutes through the same
build path (`serve.build_flagship_engine`) — shapes shrink, the
scheduler/recompile story is identical.
"""

import _bootstrap  # noqa: F401 — repo root on sys.path

_bootstrap.force_cpu_devices_from_argv()

import argparse  # noqa: E402
import signal    # noqa: E402
import sys       # noqa: E402
import time      # noqa: E402

# set by the SIGTERM handler; checked between engine steps — a signal
# handler must never call drain() re-entrantly under a running step
_DRAIN_REQUESTED = False


def _on_sigterm(signum, frame):
    global _DRAIN_REQUESTED
    _DRAIN_REQUESTED = True


def _drain_and_report(eng, finished_by_rid, live_before):
    """The ONE drain path (SIGTERM and --drain-after-steps both land
    here): drain(), account for every request that was live when the
    drain began, and return an exit code — nonzero if any of them was
    LOST (no terminal record at all) or ended in a non-ok terminal."""
    snap = eng.drain()
    for f in eng.poll():
        finished_by_rid[f.request_id] = f
    queued = len(snap["scheduler"]["pending"])
    lost = [rid for rid in live_before if rid not in finished_by_rid]
    bad = [rid for rid in live_before
           if rid in finished_by_rid
           and finished_by_rid[rid].status != "ok"]
    print(f"drain: {len(live_before)} live finished, {queued} queued "
          f"request(s) in the restorable snapshot "
          f"(serve_state_version "
          f"{snap['serve_state_version']})")
    if lost or bad:
        print(f"FAIL: drain lost request(s) {lost} / non-ok terminals "
              f"{bad}", file=sys.stderr)
        return 1
    print("serve_gpt: drain OK (no live request lost)")
    return 0


def main():
    ap = argparse.ArgumentParser(
        description="continuous-batching GPT decode demo")
    ap.add_argument("--streams", type=int, default=64,
                    help="concurrent request streams (default 64)")
    ap.add_argument("--max-new", type=int, default=None,
                    help="tokens to generate per request "
                         "(default: 16 CPU / 64 TPU)")
    ap.add_argument("--slots", type=int, default=None,
                    help="engine slots (default: min(streams, 64) — "
                         "fewer slots than streams exercises queueing)")
    ap.add_argument("--slo-ttft-p99-ms", type=float, default=None,
                    help="fail (exit nonzero) if the ledger's TTFT "
                         "p99 exceeds this many ms")
    ap.add_argument("--slo-token-p99-ms", type=float, default=None,
                    help="fail (exit nonzero) if the per-token p99 "
                         "exceeds this many ms")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request TTL: requests not served within "
                         "this many ms are evicted (terminal state "
                         "'expired') — ISSUE 14")
    ap.add_argument("--drain-after-steps", type=int, default=None,
                    help="run the graceful-drain path after N engine "
                         "steps (same code as SIGTERM) and exit — "
                         "nonzero if any live request is lost")
    ap.add_argument("--force-cpu-devices", type=int, default=0,
                    help="emulate N CPU devices (consumed by "
                         "_bootstrap before jax init)")
    args = ap.parse_args()
    if args.streams < 1:
        ap.error("--streams must be >= 1")

    import jax
    import numpy as np

    from apex_tpu.serve import (ServeSLO, build_flagship_engine,
                                measure_decode)

    on_tpu = jax.default_backend() not in ("cpu",)
    n_slots = args.slots or min(args.streams, 64)
    max_new = args.max_new or (64 if on_tpu else 16)
    eng = build_flagship_engine(on_tpu, n_slots=n_slots)
    max_new = min(max_new, eng.serve_cfg.max_new_cap)
    cfg = eng.kv_config
    print(f"engine: {n_slots} slots, {cfg.n_pages} pages x "
          f"{cfg.page_size} tokens, pool "
          f"{cfg.pool_bytes() / 2**20:.1f} MiB "
          f"({cfg.bytes_per_user(eng.serve_cfg.max_prompt_len + max_new) / 2**10:.0f}"
          f" KiB per user worst-case)")

    rng = np.random.RandomState(0)
    mp = eng.serve_cfg.max_prompt_len
    rids = []
    for _ in range(args.streams):
        plen = int(rng.randint(1, mp + 1))
        prompt = rng.randint(0, eng.model_cfg.vocab_size, plen).tolist()
        rids.append(eng.submit(prompt, max_new,
                               deadline_ms=args.deadline_ms))

    # graceful shutdown for deploys (ISSUE 14): SIGTERM requests a
    # drain; the drive loops below honor it between steps
    signal.signal(signal.SIGTERM, _on_sigterm)

    if args.drain_after_steps is not None:
        # the CI-drivable drain gate: N steps of normal serving, then
        # the exact SIGTERM path
        fins = {}
        for _ in range(args.drain_after_steps):
            if not eng.pending:
                break
            eng.step()
            for f in eng.poll():
                fins[f.request_id] = f
        live = [r.rid for r in eng._live.values()]
        return _drain_and_report(eng, fins, live)

    t0 = time.perf_counter()
    try:
        # sequential worst case bounds the drive so a scheduler
        # regression FAILS the gate instead of hanging it; the stop=
        # hook ends the drive between steps when SIGTERM lands, so
        # the drain below runs with the remainder genuinely pending
        m = measure_decode(eng, max_steps=args.streams * max_new + 64,
                           stop=lambda: _DRAIN_REQUESTED)
    except RuntimeError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    wall = time.perf_counter() - t0
    finished = m["finished"]

    if _DRAIN_REQUESTED:
        # SIGTERM landed mid-run: measure_decode returned between
        # steps with the remainder still pending — finish the live
        # slots, snapshot the queue, audit for lost work.  BEFORE the
        # stats prints: an early signal may have stopped the drive
        # with zero finished requests, and losing the drain to a
        # stats-formatting crash is the exact outcome this path exists
        # to prevent
        fins = {f.request_id: f for f in finished}
        return _drain_and_report(
            eng, fins, [r.rid for r in eng._live.values()])

    n_tok = sum(len(f.tokens) for f in finished)
    print(f"decoded {len(finished)} requests / {n_tok} tokens in "
          f"{wall:.2f}s ({n_tok / wall:.1f} tok/s end-to-end; "
          f"{m['tokens_per_sec']:.1f} tok/s post-warmup)")
    print(f"per-token latency p50 {m['p50_ms']:.2f} ms, "
          f"p99 {m['p99_ms']:.2f} ms over "
          f"{m['pure_decode_steps']} pure-decode of "
          f"{m['steps']} steps")
    sample = finished[0]
    print(f"sample request {sample.request_id}: {sample.n_prompt} prompt "
          f"tokens -> {sample.tokens[:8]}{'...' if len(sample.tokens) > 8 else ''}")
    print(f"sentry: {eng.sentry.summary()}")

    if not eng.recompile_ok:
        print("FAIL: steady-state recompile under churn — the fixed-"
              "shape contract broke (see docs/serving.md)",
              file=sys.stderr)
        return 1
    if len(finished) != args.streams:
        print(f"FAIL: {args.streams - len(finished)} request(s) never "
              "retired", file=sys.stderr)
        return 1
    n_expired = eng.telemetry.ledger.n_expired
    if args.deadline_ms is not None and n_expired:
        print(f"deadline plane: {n_expired} request(s) expired at "
              f"--deadline-ms {args.deadline_ms:g} (terminal state "
              "'expired'; balance "
              f"{eng.telemetry.ledger.balance()['ok']})")

    # the serving observatory (ISSUE 10): the request-lifecycle
    # ledger's live percentiles, and — when an SLO is given — the
    # verdict as an exit code (same posture as the sentry trip above:
    # CI holds the latency contract, not just the throughput print)
    led = eng.telemetry.ledger
    print(f"ledger: {led.n_retired} retired / {led.tokens_emitted} "
          f"tokens | ttft p50 {1e3 * led.ttft.percentile(50):.1f} ms "
          f"p99 {1e3 * led.ttft.percentile(99):.1f} ms | queue-wait "
          f"p99 {1e3 * led.queue_wait.percentile(99):.1f} ms | pool "
          f"util peak {eng.telemetry.peaks['pool_util']:.2f}")
    if (args.slo_ttft_p99_ms is not None
            or args.slo_token_p99_ms is not None):
        slo = ServeSLO(ttft_p99_ms=args.slo_ttft_p99_ms,
                       per_token_p99_ms=args.slo_token_p99_ms)
        verdict = eng.slo_verdict(slo)
        print(verdict.describe())
        if not verdict.ok:
            print("FAIL: serve SLO breach (axes: "
                  + ", ".join(b.axis for b in verdict.breaches) + ")",
                  file=sys.stderr)
            return 1
    print("serve_gpt: OK (zero steady-state recompiles)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
