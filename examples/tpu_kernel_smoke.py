"""Real-TPU lowering smoke for every Pallas kernel.

The unit suite runs the kernels in interpret mode on a CPU mesh
(tests/conftest.py), which validates numerics but NOT the Mosaic/TPU
lowering — e.g. a 1-D bias BlockSpec passes interpret mode and fails
TPU compilation.  This script compiles + executes each kernel (fwd and,
where defined, bwd) on the attached TPU chip.

Run:  python examples/tpu_kernel_smoke.py     (exits non-zero on failure)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

failures = []


def check(name, fn, *args, grad_of=None):
    try:
        out = fn(*args)
        jax.tree_util.tree_map(lambda a: np.asarray(a), out)
        if grad_of is not None:
            g = jax.grad(grad_of)(*args)
            np.asarray(jax.tree_util.tree_leaves(g)[0])
        print(f"OK   {name}", flush=True)
    except Exception as e:  # noqa: BLE001 — report-and-continue smoke
        failures.append(name)
        print(f"FAIL {name}: {type(e).__name__} {str(e)[:120]}", flush=True)


def main():
    if jax.default_backend() == "cpu":
        print("no TPU attached; kernels would run interpreted — skipping")
        return

    from apex_tpu.ops.layer_norm import fused_layer_norm, fused_rms_norm
    x = jax.random.normal(jax.random.PRNGKey(0), (256, 8, 1024), jnp.bfloat16)
    w = jnp.ones((1024,), jnp.float32)
    b = jnp.zeros((1024,), jnp.float32)
    ln_kernel = lambda x, w, b: fused_layer_norm(
        x, w, b, use_pallas_override=True)
    rms_kernel = lambda x, w: fused_rms_norm(x, w, use_pallas_override=True)
    check("layer_norm", jax.jit(ln_kernel), x, w, b,
          grad_of=lambda x, w, b: ln_kernel(x, w, b)
          .astype(jnp.float32).sum())
    check("rms_norm", jax.jit(rms_kernel), x, w,
          grad_of=lambda x, w: rms_kernel(x, w)
          .astype(jnp.float32).sum())

    from apex_tpu.ops.softmax import (
        scaled_masked_softmax,
        scaled_softmax,
        scaled_upper_triang_masked_softmax,
    )
    s = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 128, 128),
                          jnp.bfloat16)
    m = jax.random.bernoulli(jax.random.PRNGKey(2), 0.1, (4, 1, 128, 128))
    check("scaled_softmax", jax.jit(lambda a: scaled_softmax(a, 0.5)), s,
          grad_of=lambda a: scaled_softmax(a, 0.5).astype(jnp.float32).sum())
    check("scaled_masked_softmax",
          jax.jit(lambda a: scaled_masked_softmax(a, m, 0.5)), s)
    check("scaled_upper_triang",
          jax.jit(lambda a: scaled_upper_triang_masked_softmax(a, 0.5)), s)

    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
    lg = jax.random.normal(jax.random.PRNGKey(3), (512, 1000), jnp.float32)
    tg = jax.random.randint(jax.random.PRNGKey(4), (512,), 0, 1000)
    check("xentropy",
          jax.jit(lambda l: softmax_cross_entropy_loss(l, tg, smoothing=0.1)),
          lg,
          grad_of=lambda l: softmax_cross_entropy_loss(
              l, tg, smoothing=0.1).sum())

    from apex_tpu.ops.fused_dense import linear_bias, linear_gelu_linear
    from apex_tpu.ops.mlp import mlp_forward
    xx = jax.random.normal(jax.random.PRNGKey(5), (128, 512), jnp.bfloat16)
    w1 = jax.random.normal(jax.random.PRNGKey(6), (512, 1024),
                           jnp.bfloat16) * 0.02
    b1 = jnp.zeros((1024,), jnp.bfloat16)
    w2 = jax.random.normal(jax.random.PRNGKey(7), (1024, 512),
                           jnp.bfloat16) * 0.02
    b2 = jnp.zeros((512,), jnp.bfloat16)
    check("linear_bias", jax.jit(lambda x: linear_bias(x, w1, b1, "relu")),
          xx, grad_of=lambda x: linear_bias(x, w1, b1, "relu")
          .astype(jnp.float32).sum())
    check("linear_gelu_linear",
          jax.jit(lambda x: linear_gelu_linear(x, w1, b1, w2, b2)), xx)
    check("mlp_forward",
          jax.jit(lambda x: mlp_forward(x, [w1, w2], [b1, b2])), xx)

    from apex_tpu.ops.flash_attention import flash_attention
    q = jax.random.normal(jax.random.PRNGKey(8), (1, 8, 16384, 64),
                          jnp.bfloat16)
    check("flash_attention_16k",
          jax.jit(lambda q: flash_attention(q, q, q, causal=True)), q,
          grad_of=lambda q: flash_attention(q, q, q, causal=True)
          .astype(jnp.float32).sum())

    from apex_tpu.ops.flash_attention import _flash

    def dropout_checks():
        B, H, S, D = 2, 4, 512, 64
        qq = jax.random.normal(jax.random.PRNGKey(0), (B, H, S, D))
        kk = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, D))
        vv = jax.random.normal(jax.random.PRNGKey(2), (B, H, S, D))
        cc = jax.random.normal(jax.random.PRNGKey(3), (B, H, S, D))
        seed = jnp.asarray([[777]], jnp.int32)
        o1 = np.asarray(_flash(qq, kk, vv, None, None, None, 0.125, True, 0.2, None, None, 1, False, seed))
        o2 = np.asarray(_flash(qq, kk, vv, None, None, None, 0.125, True, 0.2, None, None, 1, False, seed))
        assert np.array_equal(o1, o2), "dropout mask not seed-deterministic"
        # v is linear under a fixed mask: directional FD must be exact,
        # which proves the backward kernels regenerate the forward mask
        f = lambda v_: jnp.vdot(_flash(qq, kk, v_, None, None, None, 0.125, True, 0.2, None, None, 1, False, seed),
                                cc)
        gv = jax.grad(f)(vv)
        dirv = jax.random.normal(jax.random.PRNGKey(4), vv.shape)
        fd = float(f(vv + 0.5 * dirv)) - float(f(vv - 0.5 * dirv))
        an = float(jnp.vdot(gv, dirv))
        assert abs(fd - an) < 1e-2 * abs(an) + 1e-3, (fd, an)
        # q-grad along the gradient direction (strong signal vs fp32
        # noise): proves the dq kernel's dp mask matches forward
        fq = lambda q_: jnp.vdot(_flash(q_, kk, vv, None, None, None, 0.125, True, 0.2,
                                        None, None, 1, False, seed), cc)
        g = jax.grad(fq)(qq)
        gn = float(jnp.sqrt(jnp.vdot(g, g)))
        d2 = g / gn
        fd = (float(fq(qq + 0.05 * d2)) - float(fq(qq - 0.05 * d2))) / 0.1
        assert abs(fd - gn) < 3e-2 * gn, (fd, gn)
        return True

    check("flash_dropout_mask_consistency", lambda: dropout_checks())

    from apex_tpu.ops.welford import batch_stats
    xc = jax.random.normal(jax.random.PRNGKey(9), (32, 56, 56, 64),
                           jnp.bfloat16)
    check("welford_batch_stats", jax.jit(lambda a: batch_stats(a, (0, 1, 2))),
          xc)

    from apex_tpu.ops import optimizer_kernels as K
    n = K.FLAT_TILE * 4
    p = jnp.zeros((n,), jnp.float32)
    mm = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    g = jnp.full((n,), 1e-3, jnp.bfloat16)
    check("adam_flat",
          jax.jit(lambda *a: K.adam_flat(*a, lr=1e-3, step=1,
                                         use_pallas_override=True)),
          p, mm, v, g)
    check("sgd_flat",
          jax.jit(lambda p, b, g: K.sgd_flat(
              p, b, g, lr=1e-3, momentum=0.9, first=True,
              use_pallas_override=True)), p, mm, g)
    check("adagrad_flat",
          jax.jit(lambda p, h, g: K.adagrad_flat(
              p, h, g, lr=1e-3, use_pallas_override=True)), p, mm, g)
    check("lamb_phase1",
          jax.jit(lambda m_, v_, g_, p_: K.lamb_phase1_flat(
              m_, v_, g_, p_, clip_ratio=1.0, step=1, beta1=0.9,
              beta2=0.999, eps=1e-6, weight_decay=0.01,
              use_pallas_override=True)), mm, v, g, p)

    print(("ALL OK" if not failures else f"FAILURES: {failures}"), flush=True)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
