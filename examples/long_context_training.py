"""Long-context LM training with ring attention — context parallelism
end to end.

Trains a small causal transformer on ONE packed 32k-token sequence
sharded across all devices on a `cp` mesh axis (8k tokens/device on the
8-way test mesh; the same program scales to 128k+ — see
tests/test_context_parallel.py::test_ring_attention_128k_causal_fwd_bwd).
Demonstrates the full recipe, which the reference cannot express at all
(its FMHA caps at seq 512; SURVEY §5.7):

* zigzag sequence sharding (`zigzag_shard`) so the causal ring's
  per-step work is uniform across devices;
* `ring_attention(layout="zigzag")` inside the model — blockwise flash
  chunks, lse-recompute backward, O(s_local·d) residuals;
* GLOBAL position ids ride through the zigzag permutation, so rotary/
  learned positions and the shifted-label loss stay correct;
* data-parallel-style psum of grads over cp (params replicated),
  FusedAdam on the flat buffer.

Run:  python examples/long_context_training.py --seq 32768 --steps 3
"""

from __future__ import annotations

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))

import argparse
import time

import _bootstrap

_bootstrap.force_cpu_devices_from_argv()

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import _compat as _compat  # jax 0.4.x shims (jax.shard_map)

from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.parallel.context_parallel import ring_attention, zigzag_shard


def parse():
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, default=32768)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=2)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--force-cpu-devices", type=int, default=0,
                   help="virtual CPU mesh size (applied at import)")
    return p.parse_args()


def init_params(key, a):
    ks = jax.random.split(key, 2 + 4 * a.layers)
    hd = a.hidden
    params = {
        "embed": jax.random.normal(ks[0], (a.vocab, hd)) * 0.02,
        "pos": jax.random.normal(ks[1], (a.seq, hd)) * 0.02,
    }
    for i in range(a.layers):
        k = ks[2 + 4 * i: 6 + 4 * i]
        params[f"block{i}"] = {
            "qkv": jax.random.normal(k[0], (hd, 3 * hd)) * 0.02,
            "proj": jax.random.normal(k[1], (hd, hd)) * 0.02,
            "fc1": jax.random.normal(k[2], (hd, 4 * hd)) * 0.02,
            "fc2": jax.random.normal(k[3], (4 * hd, hd)) * 0.02,
        }
    return params


def forward_loss(params, tokens, labels, pos_ids, a):
    """Shard-local forward: tokens/labels/pos_ids are (s_local,) zigzag
    shards; attention is the only cross-device op (the ring)."""
    hd, nh = a.hidden, a.heads
    x = params["embed"][tokens] + params["pos"][pos_ids]
    for i in range(a.layers):
        blk = params[f"block{i}"]
        h = _rms(x)
        qkv = h @ blk["qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # (s, hd) -> (1, nh, s, hd/nh)
            return t.reshape(-1, nh, hd // nh).transpose(1, 0, 2)[None]

        ctx = ring_attention(heads(q), heads(k), heads(v), "cp",
                             causal=True, layout="zigzag")
        ctx = ctx[0].transpose(1, 0, 2).reshape(-1, hd)
        x = x + ctx @ blk["proj"]
        h = _rms(x)
        x = x + jax.nn.gelu(h @ blk["fc1"], approximate=True) @ blk["fc2"]
    logits = _rms(x) @ params["embed"].T            # tied head (s, V)
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(lp, labels[:, None], axis=-1).mean()
    return lax.pmean(nll, "cp")


def _rms(x):
    return x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True)
                             + 1e-6)


def main():
    a = parse()
    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("cp",))
    print(f"cp mesh: {n} devices, {a.seq} tokens "
          f"({a.seq // n}/device, zigzag)")

    key = jax.random.PRNGKey(0)
    params = init_params(key, a)
    opt = FusedAdam(lr=a.lr, use_pallas=False)
    opt_state = opt.init(params)

    # ONE long "document": tokens with local structure so the model has
    # something to learn; labels are the global next-token shift,
    # computed BEFORE the zigzag permutation
    base = jax.random.randint(jax.random.PRNGKey(1), (a.seq,), 0, a.vocab)
    tokens = (base + jnp.roll(base, 1)) % a.vocab   # order-1 structure
    labels = jnp.roll(tokens, -1)
    pos_ids = jnp.arange(a.seq)
    tz, lz, pz = (zigzag_shard(x[None], n, axis=1)[0]
                  for x in (tokens, labels, pos_ids))

    # params live in the flat optimizer state; pull the tree per step
    def step_fn(opt_state, t, l, p_ids):
        from apex_tpu.optimizers import flat as F
        p_tree = F.unflatten(opt_state.params, opt.spec)

        def loss_fn(p):
            return forward_loss(p, t, l, p_ids, a)

        loss, grads = jax.value_and_grad(loss_fn)(p_tree)
        grads = jax.tree_util.tree_map(
            lambda g: lax.pmean(g, "cp"), grads)
        _, opt_state = opt.step(opt_state, grads)
        return opt_state, loss

    step = jax.jit(shard_map(
        step_fn, mesh=mesh,
        in_specs=(P(), P("cp"), P("cp"), P("cp")),
        out_specs=(P(), P()), check_vma=False))

    loss = float("nan")
    for i in range(a.steps):
        t0 = time.perf_counter()
        opt_state, loss = step(opt_state, tz, lz, pz)
        loss = float(loss)
        dt = time.perf_counter() - t0
        print(f"step {i}: loss {loss:.4f}  {dt:.1f}s  "
              f"({a.seq / dt:.0f} tok/s)")
    return loss


if __name__ == "__main__":
    main()
