"""FusedAdam step-time micro-benchmark at large parameter counts.

TPU counterpart of the driver metric "FusedAdam step ms @ 1B params"
(BASELINE.json; the reference's tests/L0/run_optimizers are
correctness-only).  One fused Pallas Adam launch over a single flat
donated buffer — the design that replaces amp_C.multi_tensor_adam's
chunked ≤110-tensor launches (csrc/multi_tensor_apply.cuh:15-16).

Run:  python examples/bench_optimizers.py [n_params ...]
Prints one JSON line per size.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def bench_adam(n: int, param_dtype=jnp.float32, iters: int = 20,
               warmup: int = 3) -> dict:
    from apex_tpu.ops import optimizer_kernels as K

    on_tpu = jax.default_backend() not in ("cpu",)

    # tile-aligned, as FusedAdam.init allocates (flatten(pad_to=FLAT_TILE)):
    # unaligned buffers force a pad copy that breaks in-place aliasing
    n = -(-n // K.FLAT_TILE) * K.FLAT_TILE
    p = jnp.zeros((n,), param_dtype)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    g = jnp.full((n,), 1e-3, jnp.bfloat16 if on_tpu else param_dtype)

    def _step(p, m, v, g):
        return K.adam_flat(p, m, v, g, lr=1e-3, step=10,
                           weight_decay=0.01,
                           use_pallas_override=on_tpu or None)

    # donate: the aliased Pallas call updates p/m/v in place
    step = jax.jit(_step, donate_argnums=(0, 1, 2))

    for _ in range(warmup):
        p, m, v = step(p, m, v, g)
    np.asarray(p[:1])  # sync
    t0 = time.perf_counter()
    for _ in range(iters):
        p, m, v = step(p, m, v, g)
    np.asarray(p[:1])
    ms = (time.perf_counter() - t0) / iters * 1e3
    # HBM bytes touched: p read+write, m/v read+write (fp32), one bf16 g read
    itemsize = jnp.dtype(param_dtype).itemsize
    bytes_moved = n * (2 * itemsize + 4 * 4 + 2) if on_tpu else None
    return {
        "metric": f"fused_adam_step_ms_at_{n/1e9:.2g}B_params",
        "value": round(ms, 3),
        "unit": "ms",
        "dtype": str(jnp.dtype(param_dtype)),
        "gb_per_s": round(bytes_moved / (ms / 1e3) / 1e9, 1)
        if bytes_moved else None,
        "vs_baseline": 1.0,
    }


def main():
    sizes = [int(float(a)) for a in sys.argv[1:]] or [2**27, 10**9]
    if jax.default_backend() == "cpu":
        sizes = [2**20]
    for n in sizes:
        dt = jnp.float32
        try:
            print(json.dumps(bench_adam(n, dt)))
        except Exception as e:  # OOM at 1B fp32 on 16GB: retry bf16 params
            print(f"# {n} fp32 failed ({type(e).__name__}); retrying bf16",
                  file=sys.stderr)
            print(json.dumps(bench_adam(n, jnp.bfloat16)))


if __name__ == "__main__":
    main()
