"""ZeRO-2 per-device memory report — the point of the sharded optimizer.

Builds GPT-1.3B (bf16 compute) with fp32-master DistributedFusedAdam
over a dp=8 mesh, AOT-compiles the full train step, and prints XLA's
per-device memory analysis next to the analytic accounting — the
multi-chip data point the fp32-master path exists for (a 1.3B fp32
p+m+v state is 15.7 GB: it cannot fit ONE 16 GB chip unsharded, and
each dp=8 shard holds 1/8 of it).

≡ the reference's DistributedFusedAdam memory rationale
(apex/contrib/optimizers/distributed_fused_adam.py:199-212) and the
store_params/grad_sync_dtype sweeps of its test_dist_adam.py.

Run (any host — forces an 8-device virtual CPU mesh when needed):
  python examples/zero_memory_report.py [--run] [--dp 8]
`--run` additionally executes one step (needs ~90 GB host RAM at 1.3B).
"""

from __future__ import annotations

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=8)
    ap.add_argument("--run", action="store_true",
                    help="also execute one step (large host RAM)")
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--heads", type=int, default=32)
    args = ap.parse_args()

    import jax
    try:
        n_vis = len(jax.devices())
    except RuntimeError:
        n_vis = 0
    if n_vis < args.dp:
        # same virtual-mesh bootstrap as __graft_entry__.dryrun_multichip
        import subprocess
        here = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
        env = dict(_os.environ)
        env["PYTHONPATH"] = here + _os.pathsep + env.get("PYTHONPATH", "")
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"jax.config.update('jax_num_cpu_devices', {args.dp}); "
            f"import sys; sys.argv = {['zero_memory_report'] + _sys.argv[1:]!r}; "
            "import runpy; runpy.run_path("
            f"{_os.path.abspath(__file__)!r}, run_name='__main__')"
        )
        raise SystemExit(subprocess.run(
            [_sys.executable, "-c", code], env=env, cwd=here).returncode)

    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam, DistributedFusedAdamState)
    from apex_tpu.parallel import mesh as M

    dp = args.dp
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:dp])
    cfg = GPTConfig(vocab_size=50304, seq_len=512, hidden=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    dropout=0.0, dtype=jnp.bfloat16,
                    logits_dtype=jnp.bfloat16, remat=True)
    model = GPT(cfg)
    pshapes = jax.eval_shape(lambda k: model.init(k), jax.random.PRNGKey(0))
    n = sum(int(jnp.prod(jnp.asarray(l.shape)))
            for l in jax.tree_util.tree_leaves(pshapes))
    print(f"model: {n/1e9:.3f}B params, dp={dp}, fp32 master + bf16 "
          f"grad sync")

    opt = DistributedFusedAdam(num_shards=dp, lr=1e-4,
                               grad_sync_dtype=jnp.bfloat16,
                               use_pallas=False)
    sspec = DistributedFusedAdamState(P(), P("dp"), P("dp"), P("dp"))
    init = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                             out_specs=sspec, check_vma=False))

    def zstep(state, tokens, labels):
        p = opt.full_params(state)
        loss, grads = jax.value_and_grad(
            lambda pp: model.loss(pp, tokens, labels))(p)
        _, state = opt.step(state, grads)
        return state, jax.lax.pmean(loss, "dp")

    batch = dp  # one tiny sequence per rank
    tokens_s = jax.ShapeDtypeStruct((batch, cfg.seq_len), jnp.int32)
    params_s = pshapes
    state_s = jax.eval_shape(init, params_s)
    step = jax.jit(shard_map(zstep, mesh=mesh,
                             in_specs=(sspec, P("dp"), P("dp")),
                             out_specs=(sspec, P()), check_vma=False),
                   donate_argnums=(0,))
    lowered = step.lower(state_s, tokens_s, tokens_s)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    gb = 1e9
    state_total = sum(
        int(jnp.prod(jnp.asarray(b.shape))) * b.dtype.itemsize
        for b in jax.tree_util.tree_leaves(state_s))
    print(f"fp32 p+m+v total (sharded over dp): {state_total/gb:.2f} GB "
          f"-> {state_total/dp/gb:.2f} GB/device")
    print(f"XLA per-device: arguments {ma.argument_size_in_bytes/gb:.2f} "
          f"GB, temps {ma.temp_size_in_bytes/gb:.2f} GB, output "
          f"{ma.output_size_in_bytes/gb:.2f} GB (output aliases donated "
          "state)")
    peak = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    print(f"XLA per-device requirement ~= {peak/gb:.2f} GB "
          f"({'fits' if peak < 15.7e9 else 'exceeds'} one 16 GB v5e chip)")

    if args.run:
        import numpy as np
        params = model.init(jax.random.PRNGKey(0))
        state = init(params)
        del params
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, cfg.seq_len), 0,
                                    cfg.vocab_size)
        # reuse the AOT executable — step(...) would retrace+recompile
        state, loss = compiled(state, tokens,
                               jnp.roll(tokens, -1, axis=1))
        print("one ZeRO step executed; loss =", float(np.asarray(loss)))


if __name__ == "__main__":
    main()
