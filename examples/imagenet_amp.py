"""End-to-end AMP ResNet training — the canonical example.

≡ examples/imagenet/main_amp.py: ResNet-50, AMP opt levels O0-O3,
data-parallel mesh, SyncBatchNorm, fused optimizer, prefetching loader,
and the images/sec Speed meter (main_amp.py:386-397).

Run (synthetic data, any device count):
  python examples/imagenet_amp.py --opt-level O1 --batch-size 64 \
      --arch resnet50 --iters 100
"""

from __future__ import annotations

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.checkpoint import save_checkpoint
from apex_tpu.csrc import gather_rows, shuffle_indices
from apex_tpu.models.resnet import ResNet
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M


def parse():
    p = argparse.ArgumentParser()
    p.add_argument("--arch", default="resnet50")
    p.add_argument("--opt-level", default="O1",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--batch-size", type=int, default=64,
                   help="global batch size")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--save", default=None)
    return p.parse_args()


def main():
    args = parse()
    mesh = M.initialize_model_parallel()
    dp = M.get_data_parallel_world_size()
    print(f"devices: {jax.device_count()}  mesh dp={dp}")

    model = ResNet(args.arch, num_classes=args.num_classes,
                   axis_name="dp")
    params, mstate = model.init(jax.random.PRNGKey(0))
    amp_state = amp.initialize(opt_level=args.opt_level)
    if amp_state.policy.param_dtype != jnp.float32:
        params = amp.convert_network(params, amp_state.policy.param_dtype)

    def loss_fn(p, ms, batch):
        x, y = batch
        logits, new_ms = model.apply(p, ms, x, training=True)
        return jnp.mean(softmax_cross_entropy_loss(
            logits.astype(jnp.float32), y)), new_ms

    opt = FusedSGD(lr=args.lr, momentum=args.momentum,
                   weight_decay=args.weight_decay)
    state = opt.init(params)
    scaler = amp_state.loss_scalers[0]
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               with_state=True)

    # synthetic dataset, pre-staged on device (≡ data_prefetcher,
    # main_amp.py:265 — the host side uses the native threaded gather)
    n_samples = max(args.batch_size * 2, 256)
    dataset_host = np.random.randn(
        n_samples, args.image_size, args.image_size, 3).astype(np.float32)
    labels_host = np.random.randint(0, args.num_classes, n_samples)
    dataset = jnp.asarray(dataset_host)   # one upload
    labels_all = jnp.asarray(labels_host)

    t0 = time.perf_counter()
    for it in range(args.iters):
        idx = jnp.asarray(
            shuffle_indices(n_samples, it)[: args.batch_size])
        x = jnp.take(dataset, idx, axis=0)      # device-side gather
        y = jnp.take(labels_all, idx, axis=0)
        state, scaler, mstate, loss = step(state, scaler, mstate, (x, y))
        if (it + 1) % args.print_freq == 0:
            _ = np.asarray(loss)
            dt = (time.perf_counter() - t0) / args.print_freq
            # ≡ the Speed meter (main_amp.py:386-397)
            print(f"iter {it+1}  loss {float(loss):.4f}  "
                  f"Speed {args.batch_size / dt:.1f} img/sec  "
                  f"time/iter {dt*1000:.1f} ms  "
                  f"loss_scale {float(scaler.scale):.0f}")
            t0 = time.perf_counter()

    if args.save:
        save_checkpoint(args.save, {
            "opt_state": state, "model_state": mstate,
            "amp": amp.state_dict(amp_state)})
        print(f"saved to {args.save}")


if __name__ == "__main__":
    main()
