"""GPT scaling sweep — iteration time vs model size.

≡ tests/L0/run_transformer/gpt_scaling_test.py:7-112: sweeps hidden
sizes, runs the standalone GPT, parses/prints "Average Iteration Time",
and reports s/iter vs parameter count.

  python examples/gpt_scaling_test.py --steps 5 --batch-size 8
"""

from __future__ import annotations

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt import GPT, GPTConfig
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.parallel import mesh as M
from apex_tpu.transformer.training import (
    init_sharded_optimizer,
    make_tp_dp_train_step,
)

SWEEP = [  # (hidden, layers, heads) ≈ gpt_scaling_test.py size points
    (512, 8, 8),
    (1024, 12, 16),
    (1536, 16, 16),
    (2048, 24, 32),
]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--max-hidden", type=int, default=2048)
    p.add_argument("--min-hidden", type=int, default=0)
    p.add_argument("--optimizer", choices=["adam", "sgd"], default="adam")
    p.add_argument("--pure-half", action="store_true",
                   help="O3-style bf16 optimizer state (master_dtype="
                        "bfloat16): p+m+v at 6 B/param lets the 1.3B "
                        "point train on a single 16GB chip")
    p.add_argument("--donate", action=argparse.BooleanOptionalAction,
                   default=True)
    args = p.parse_args()

    for hidden, layers, heads in SWEEP:
        if hidden > args.max_hidden or hidden < args.min_hidden:
            continue
        M.destroy_model_parallel()
        mesh = M.initialize_model_parallel(
            tensor_model_parallel_size=args.tp)
        cfg = GPTConfig(vocab_size=50304, seq_len=args.seq_len,
                        hidden=hidden, num_layers=layers, num_heads=heads,
                        dtype=jnp.bfloat16, remat=True,
                        use_flash_attention=True,
                        sequence_parallel=args.tp > 1)
        model = GPT(cfg)
        params = model.init(jax.random.PRNGKey(0))
        n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
        mdt = jnp.bfloat16 if args.pure_half else jnp.float32
        if args.optimizer == "sgd":
            from apex_tpu.optimizers.fused_sgd import FusedSGD
            opt = FusedSGD(lr=1e-3, momentum=0.9, master_dtype=mdt)
        else:
            opt = FusedAdam(lr=1e-4, master_dtype=mdt)
        opt_state = init_sharded_optimizer(opt, model, params, mesh)
        step = make_tp_dp_train_step(model, opt, mesh, donate=args.donate)
        del params  # the donated flat state owns the master copy
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (args.batch_size, args.seq_len), 0,
            cfg.vocab_size)
        labels = jnp.roll(tokens, -1, axis=1)
        opt_state, loss = step(opt_state, tokens, labels)  # compile
        _ = np.asarray(loss)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            opt_state, loss = step(opt_state, tokens, labels)
        _ = np.asarray(loss)
        dt = (time.perf_counter() - t0) / args.steps
        # ≡ the parsed metric (gpt_scaling_test.py:13-47)
        print(f"hidden={hidden} params={n_params/1e6:.0f}M  "
              f"Average Iteration Time: {dt:.3f} s  "
              f"({args.batch_size*args.seq_len/dt:.0f} tokens/s)")


if __name__ == "__main__":
    main()
