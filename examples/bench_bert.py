"""BERT-Large pretraining-step benchmark — FusedLAMB + fused kernels.

≡ the BASELINE config "BERT-Large pretraining with FusedLAMB +
fused_dense": one full MLM+NSP training step (fwd + bwd + LAMB) on one
chip, sequences/sec printed as JSON.

Run:  python examples/bench_bert.py [--batch 8] [--seq 512] [--iters 10]
"""

from __future__ import annotations

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.bert import Bert, BertConfig
from apex_tpu.optimizers.fused_lamb import FusedLAMB
from apex_tpu.parallel import mesh as M
from apex_tpu.transformer.training import (
    init_sharded_optimizer,
    make_tp_dp_train_step,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--batch-sweep", type=str, default=None,
                    help="comma-separated batches to sweep (round 6: the "
                    "b32 knee question — LAMB's pass is batch-invariant, "
                    "so seq/s keeps rising until compile/HBM fails; "
                    "e.g. '16,32,40,48')")
    args = ap.parse_args()

    on_tpu = jax.default_backend() not in ("cpu",)
    if not on_tpu:
        args.batch, args.seq, args.iters = 2, 64, 2

    if args.batch_sweep:
        if not on_tpu:
            # the child self-clamps to b2/s64 off-TPU, so every point
            # would be the same measurement wearing different labels
            print("--batch-sweep needs a TPU backend; got "
                  f"{jax.default_backend()}", file=_sys.stderr)
            _sys.exit(2)
        import subprocess
        for b in (int(x) for x in args.batch_sweep.split(",") if x):
            cmd = [_sys.executable, _os.path.abspath(__file__),
                   "--batch", str(b), "--seq", str(args.seq),
                   "--iters", str(args.iters)]
            # fresh process per point: a failed compile (b64 round 4)
            # must not poison the later points' allocator
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=1800)
            except subprocess.TimeoutExpired:
                print(f"b{b}: FAIL timeout (1800s)", flush=True)
                continue
            # reverse-scan for the JSON line (≡ bench._run_isolated): a
            # plugin log line after the JSON must not eat the result
            line = "<no json output>"
            for cand in reversed(r.stdout.strip().splitlines()):
                try:
                    d = json.loads(cand)
                except ValueError:
                    continue
                if isinstance(d, dict) and "metric" in d:
                    line = cand
                    break
            print(f"b{b}: {line if r.returncode == 0 else 'FAIL ' + r.stderr.strip()[-120:]}",
                  flush=True)
        return

    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    # flash attention measured fastest at seq 512 too (round-3 sweep:
    # 73.6 vs 66.6 seq/s dense; bf16 MLM logits were neutral-to-worse)
    cfg = (BertConfig(seq_len=args.seq, dtype=jnp.bfloat16,
                      use_flash_attention=True) if on_tpu else
           BertConfig(seq_len=args.seq, hidden=128, num_layers=2,
                      num_heads=4, dtype=jnp.bfloat16))
    model = Bert(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedLAMB(lr=1e-4, weight_decay=0.01)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    del params

    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (args.batch, args.seq), 0,
                                cfg.vocab_size)
    mlm_labels = jnp.roll(tokens, -1, axis=1)
    loss_mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.15,
                                     (args.batch, args.seq))
    nsp = jax.random.randint(jax.random.PRNGKey(3), (args.batch,), 0, 2)

    def loss_fn(p, tokens, labels):
        return model.loss(p, tokens, labels, loss_mask, nsp_labels=nsp)

    step = make_tp_dp_train_step(model, opt, mesh, loss_fn=loss_fn,
                                 donate=True)

    for _ in range(2):
        opt_state, loss = step(opt_state, tokens, mlm_labels)
    np.asarray(loss)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        opt_state, loss = step(opt_state, tokens, mlm_labels)
    np.asarray(loss)
    dt = (time.perf_counter() - t0) / args.iters
    print(json.dumps({
        "metric": "bert_large_lamb_seqs_per_sec_per_chip",
        "value": round(args.batch / dt, 1),
        "unit": "sequences/s",
        "s_per_iter": round(dt, 4),
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
