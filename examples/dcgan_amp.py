"""DCGAN under AMP — the multi-model / multi-optimizer / multi-loss demo.

≡ examples/dcgan/main_amp.py in the reference: two networks (G, D),
two optimizers, and THREE losses per iteration (errD_real, errD_fake,
errG) each with its own loss scaler — exercising
`amp.initialize(num_losses=3)` the way the reference does
(main_amp.py: amp.initialize([netD, netG], [optimizerD, optimizerG],
num_losses=3).

TPU-first differences: NHWC layout, transposed convs via
`lax.conv_transpose`, both G and D steps fused into single jitted
updates with per-loss dynamic scaler states, synthetic data by default
(the reference's `--dataset fake` mode) so the example runs anywhere.

Run (tiny, CPU ok):
    python examples/dcgan_amp.py --image-size 32 --iters 20
"""

from __future__ import annotations

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import time

import _bootstrap

_bootstrap.force_cpu_devices_from_argv()

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu import amp
from apex_tpu.amp import scaler as scaler_lib
from apex_tpu.optimizers.fused_adam import FusedAdam


# ---------------------------------------------------------------- models
def _conv_init(key, kh, kw, cin, cout, dtype=jnp.float32):
    std = 0.02  # DCGAN init: N(0, 0.02) (main_amp.py weights_init)
    return jax.random.normal(key, (kh, kw, cin, cout), dtype) * std


def _bn_params(c):
    return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}


def _bn(x, p, eps=1e-5):
    # Per-batch BN (training-mode stats only, as in the GAN training loop).
    mean = jnp.mean(x, axis=(0, 1, 2))
    var = jnp.var(x, axis=(0, 1, 2))
    inv = lax.rsqrt(var + eps)
    return (x - mean) * inv * p["scale"] + p["bias"]


class Generator:
    """z (N,1,1,nz) → image (N,S,S,nc); mirrors the reference netG
    (ConvTranspose2d stack, BN+ReLU, tanh head)."""

    def __init__(self, image_size=32, nz=100, ngf=64, nc=3):
        assert image_size in (32, 64)
        self.image_size, self.nz, self.ngf, self.nc = image_size, nz, ngf, nc
        # (cin, cout, stride) per deconv layer, 4x4 kernels throughout.
        mult = image_size // 8  # 4 for 32, 8 for 64
        chain = [(nz, ngf * mult, 1)]
        while mult > 1:
            chain.append((ngf * mult, ngf * mult // 2, 2))
            mult //= 2
        chain.append((ngf, nc, 2))
        self.chain = chain

    def init(self, key):
        params = []
        for i, (cin, cout, _s) in enumerate(self.chain):
            key, k = jax.random.split(key)
            p = {"w": _conv_init(k, 4, 4, cin, cout)}
            if i < len(self.chain) - 1:
                p["bn"] = _bn_params(cout)
            params.append(p)
        return params

    def __call__(self, params, z, policy=None):
        x = z
        compute = (policy.cast_to_compute if policy else (lambda t: t))
        for i, ((_cin, _cout, s), p) in enumerate(zip(self.chain, params)):
            pad = "VALID" if i == 0 else "SAME"
            x = lax.conv_transpose(
                compute(x), compute(p["w"]), strides=(s, s), padding=pad,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if i < len(self.chain) - 1:
                x = jax.nn.relu(_bn(x.astype(jnp.float32), p["bn"]))
            else:
                x = jnp.tanh(x.astype(jnp.float32))
        return x


class Discriminator:
    """image → logit; Conv stride-2 stack, LeakyReLU(0.2), BN."""

    def __init__(self, image_size=32, ndf=64, nc=3):
        mult, chain, cin = 1, [], nc
        size = image_size
        while size > 4:
            chain.append((cin, ndf * mult, 2))
            cin, mult, size = ndf * mult, mult * 2, size // 2
        chain.append((cin, 1, 1))  # 4x4 VALID → 1x1 logit
        self.chain = chain

    def init(self, key):
        params = []
        for i, (cin, cout, _s) in enumerate(self.chain):
            key, k = jax.random.split(key)
            p = {"w": _conv_init(k, 4, 4, cin, cout)}
            if 0 < i < len(self.chain) - 1:
                p["bn"] = _bn_params(cout)
            params.append(p)
        return params

    def __call__(self, params, x, policy=None):
        compute = (policy.cast_to_compute if policy else (lambda t: t))
        for i, ((_cin, _cout, s), p) in enumerate(zip(self.chain, params)):
            pad = "VALID" if i == len(self.chain) - 1 else "SAME"
            x = lax.conv_general_dilated(
                compute(x), compute(p["w"]), window_strides=(s, s),
                padding=pad, dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if i < len(self.chain) - 1:
                if "bn" in p:
                    x = _bn(x.astype(jnp.float32), p["bn"])
                x = jax.nn.leaky_relu(x.astype(jnp.float32), 0.2)
        return x.reshape(x.shape[0])  # logits


def bce_with_logits(logits, target):
    # stable BCEWithLogitsLoss ≡ nn.BCELoss(sigmoid) in the reference
    return jnp.mean(jnp.clip(logits, 0) - logits * target +
                    jnp.log1p(jnp.exp(-jnp.abs(logits))))


# ---------------------------------------------------------------- steps
def make_steps(G, D, optG, optD, policy):
    """Two jitted updates; three independent loss-scaler states
    (errD_real → scaler 0, errD_fake → scaler 1, errG → scaler 2), the
    loss_id plumbing of amp.scale_loss(..., loss_id=i)."""

    dynamic = policy.loss_scale == "dynamic"

    def d_real_loss(dp, real, s):
        l = bce_with_logits(D(dp, real, policy), 1.0)
        return scaler_lib.scale_loss(s, l), l

    def d_fake_loss(dp, fake, s):
        l = bce_with_logits(D(dp, fake, policy), 0.0)
        return scaler_lib.scale_loss(s, l), l

    def g_loss(gp, dp, z, s_g):
        fake = G(gp, z, policy)
        lg_ = bce_with_logits(D(dp, fake, policy), 1.0)
        return scaler_lib.scale_loss(s_g, lg_), lg_

    @jax.jit
    def d_step(dp, d_state, gp, real, z, s_real, s_fake):
        # Two backwards, one per loss/scaler, grads accumulated — exactly
        # the reference's errD_real.backward(); errD_fake.backward() under
        # separate loss_ids.  Each contribution is unscaled by ITS OWN
        # scaler before summing, so the scalers may diverge freely.
        fake = lax.stop_gradient(G(gp, z, policy))
        (_, lr_), g_r = jax.value_and_grad(
            d_real_loss, has_aux=True)(dp, real, s_real)
        (_, lf_), g_f = jax.value_and_grad(
            d_fake_loss, has_aux=True)(dp, fake, s_fake)
        g_r, found_r = scaler_lib.unscale(s_real, g_r)
        g_f, found_f = scaler_lib.unscale(s_fake, g_f)
        grads = jax.tree.map(jnp.add, g_r, g_f)
        found = jnp.logical_or(found_r, found_f)
        s_real2 = scaler_lib.update(s_real, found_r, dynamic=dynamic)
        s_fake2 = scaler_lib.update(s_fake, found_f, dynamic=dynamic)
        dp, d_state = optD.step(d_state, grads, found_inf=found)
        return dp, d_state, s_real2, s_fake2, lr_ + lf_

    @jax.jit
    def g_step(gp, g_state, dp, z, s_g):
        (_, lg_), grads = jax.value_and_grad(
            g_loss, has_aux=True)(gp, dp, z, s_g)
        grads, found = scaler_lib.unscale(s_g, grads)
        s_g2 = scaler_lib.update(s_g, found, dynamic=dynamic)
        gp, g_state = optG.step(g_state, grads, found_inf=found)
        return gp, g_state, s_g2, lg_

    return d_step, g_step


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--nz", type=int, default=100)
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--lr", type=float, default=2e-4)
    ap.add_argument("--beta1", type=float, default=0.5)
    ap.add_argument("--opt-level", default="O1")
    ap.add_argument("--force-cpu-devices", type=int, default=0,
                    help="run on N emulated CPU devices (consumed "
                         "before backend init, above)")
    args = ap.parse_args()

    G = Generator(args.image_size, args.nz)
    D = Discriminator(args.image_size)
    kg, kd, kdata = jax.random.split(jax.random.PRNGKey(0), 3)
    gp, dp = G.init(kg), D.init(kd)
    # ≡ amp.initialize([netD, netG], [optD, optG], num_losses=3): under
    # O2/O3 this casts both nets' params (BN kept fp32 under O2).
    (gp, dp), amp_state = amp.initialize((gp, dp),
                                         opt_level=args.opt_level,
                                         num_losses=3)
    policy = amp_state.policy
    s_real, s_fake, s_g = amp_state.loss_scalers
    optG = FusedAdam(lr=args.lr, betas=(args.beta1, 0.999))
    optD = FusedAdam(lr=args.lr, betas=(args.beta1, 0.999))
    g_state, d_state = optG.init(gp), optD.init(dp)

    d_step, g_step = make_steps(G, D, optG, optD, policy)

    t0 = time.time()
    for it in range(args.iters):
        kdata, kz1, kz2, kx = jax.random.split(kdata, 4)
        real = jax.random.uniform(kx, (args.batch_size, args.image_size,
                                       args.image_size, 3)) * 2 - 1
        z1 = jax.random.normal(kz1, (args.batch_size, 1, 1, args.nz))
        z2 = jax.random.normal(kz2, (args.batch_size, 1, 1, args.nz))
        dp, d_state, s_real, s_fake, errD = d_step(
            dp, d_state, gp, real, z1, s_real, s_fake)
        gp, g_state, s_g, errG = g_step(gp, g_state, dp, z2, s_g)
        if it % 10 == 0 or it == args.iters - 1:
            print(f"[{it}/{args.iters}] Loss_D {float(errD):.4f} "
                  f"Loss_G {float(errG):.4f} "
                  f"scale {float(s_g.scale):.0f}")
    dt = time.time() - t0
    print(f"{args.iters} iters in {dt:.1f}s "
          f"({args.iters * args.batch_size / dt:.0f} img/s)")


if __name__ == "__main__":
    main()
