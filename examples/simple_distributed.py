"""Minimal data-parallel training ≡ examples/simple/distributed/
(distributed_data_parallel.py): the smallest DDP-equivalent program.

  python examples/simple_distributed.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M


def main():
    mesh = M.initialize_model_parallel()
    print("mesh:", dict(mesh.shape))

    X = jax.random.normal(jax.random.PRNGKey(0), (64, 8))
    w_true = jax.random.normal(jax.random.PRNGKey(1), (8, 1))
    Y = X @ w_true

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y) ** 2)

    opt = FusedSGD(lr=0.2)
    state = opt.init({"w": jnp.zeros((8, 1))})
    step = ddp.make_train_step(loss_fn, opt, mesh,
                               batch_spec=(P("dp"), P("dp")))
    for i in range(20):
        state, _, loss = step(state, None, (X, Y))
        if i % 5 == 0:
            print(f"step {i}: loss {float(loss):.6f}")
    print("final loss:", float(loss))


if __name__ == "__main__":
    main()
